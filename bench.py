"""Benchmark: the BASELINE.json workloads on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

Two suites (BASELINE.md):
- **ssb100m**: an SSB-shaped 100M-row lineorder table, the five BASELINE
  configs — (1) full-scan group-by SUM (baseballStats shape), (2) range
  filter + SUM (Q1.x shape), (3) IN + BETWEEN filter agg (inverted-index
  shape), (4) high-cardinality group-by with COUNT/AVG/DISTINCTCOUNTHLL
  (NYC-taxi shape), (5) star-tree-accelerated 3-dim group-by (Q4.x shape).
- **taxi12m**: round-1's 12M-row suite, kept as a regression guard.

The headline is rows-scanned/s/chip on the 100M high-cardinality group-by.
vs_baseline compares against the in-process numpy host executor on one
segment, scaled to the full table (stand-in until a real Pinot 32-vCPU run
is recorded — BASELINE.md: "published": {}).

Reference harness shape: pinot-perf/.../BenchmarkQueries.java:78,159-167.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

CACHE = os.path.join(tempfile.gettempdir(), "pinot_tpu_bench_v5")

TAXI_SEGMENTS = 8
TAXI_ROWS = 1_500_000
SSB_SEGMENTS = 8
SSB_ROWS = 12_500_000  # x8 = 100M
BSKIP_SEGMENTS = 4
BSKIP_ROWS = 2_500_000  # x4 = 10M (the block-skip selectivity sweep)


def _built(d, n):
    return all(
        os.path.exists(os.path.join(d, f"s{i}", "metadata.json")) for i in range(n)
    )


def build_taxi():
    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import (
        IndexingConfig,
        StarTreeIndexConfig,
        TableConfig,
    )
    from pinot_tpu.storage.creator import build_segment

    out_base = os.path.join(CACHE, "taxi")
    if _built(out_base, TAXI_SEGMENTS):
        return
    schema = Schema.build(
        name="bench",
        dimensions=[
            ("zone", DataType.STRING),
            ("hour", DataType.INT),
            ("vendor", DataType.STRING),
        ],
        metrics=[("fare", DataType.INT), ("distance", DataType.DOUBLE)],
    )
    cfg = TableConfig(
        table_name="bench",
        indexing=IndexingConfig(
            star_tree_configs=[
                StarTreeIndexConfig(
                    dimensions_split_order=["zone", "hour", "vendor"],
                    function_column_pairs=["SUM__fare", "COUNT__*"],
                )
            ]
        ),
    )
    rng = np.random.default_rng(42)
    zones = np.array([f"zone_{i:03d}" for i in range(260)])
    vendors = np.array([f"v{i}" for i in range(8)])
    for i in range(TAXI_SEGMENTS):
        out = os.path.join(out_base, f"s{i}")
        if os.path.exists(os.path.join(out, "metadata.json")):
            continue
        n = TAXI_ROWS
        cols = {
            "zone": zones[rng.integers(0, 260, n)],
            "hour": rng.integers(0, 24, n).astype(np.int32),
            "vendor": vendors[rng.integers(0, 8, n)],
            "fare": rng.integers(100, 10_000, n).astype(np.int32),
            "distance": np.round(rng.uniform(0.1, 50.0, n), 2),
        }
        build_segment(schema, cols, out, cfg, f"s{i}")


def build_ssb():
    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import (
        IndexingConfig,
        StarTreeIndexConfig,
        TableConfig,
    )
    from pinot_tpu.storage.creator import build_segment

    out_base = os.path.join(CACHE, "ssb")
    if _built(out_base, SSB_SEGMENTS):
        return
    schema = Schema.build(
        name="lineorder",
        dimensions=[
            ("d_year", DataType.INT),
            ("c_region", DataType.STRING),
            ("s_nation", DataType.STRING),
            ("lo_suppkey", DataType.INT),
            ("lo_custkey", DataType.INT),
            ("lo_orderdate", DataType.INT),
            ("lo_discount", DataType.INT),
        ],
        metrics=[("lo_quantity", DataType.INT), ("lo_revenue", DataType.INT)],
    )
    cfg = TableConfig(
        table_name="lineorder",
        indexing=IndexingConfig(
            inverted_index_columns=["lo_suppkey"],
            star_tree_configs=[
                StarTreeIndexConfig(
                    dimensions_split_order=["d_year", "c_region", "s_nation"],
                    function_column_pairs=["SUM__lo_revenue", "COUNT__*"],
                ),
                # the q4 shape: high-card group-by + HLL — sketch (register
                # plane) pre-aggregation in the cube
                StarTreeIndexConfig(
                    dimensions_split_order=["lo_suppkey"],
                    function_column_pairs=[
                        "COUNT__*", "SUM__lo_quantity",
                        "DISTINCTCOUNTHLL__lo_custkey",
                    ],
                ),
            ],
        ),
    )
    rng = np.random.default_rng(7)
    nations = np.array([f"nation_{i:02d}" for i in range(25)])
    regions = np.array(["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDEAST"])
    for i in range(SSB_SEGMENTS):
        out = os.path.join(out_base, f"s{i}")
        if os.path.exists(os.path.join(out, "metadata.json")):
            continue
        n = SSB_ROWS
        cols = {
            "d_year": rng.integers(1992, 1999, n).astype(np.int32),
            "c_region": regions[rng.integers(0, 5, n)],
            "s_nation": nations[rng.integers(0, 25, n)],
            "lo_suppkey": rng.integers(0, 2000, n).astype(np.int32),
            "lo_custkey": rng.integers(0, 100_000, n).astype(np.int32),
            # date-like ints spanning 1992-01-01..1998-08-02 (SSB's range) so
            # Q1.x's 1993 BETWEEN actually selects rows (a prior generator
            # capped at 19922405 — every segment min/max-pruned and "q2" was
            # a 1.6ms no-op)
            "lo_orderdate": (
                19920101
                + (rng.integers(0, 7, n) * 10000)
                + (rng.integers(0, 12, n) * 100)
                + rng.integers(0, 28, n)
            ).astype(np.int32),
            "lo_discount": rng.integers(0, 11, n).astype(np.int32),
            "lo_quantity": rng.integers(1, 51, n).astype(np.int32),
            "lo_revenue": rng.integers(1000, 6_000_000, n).astype(np.int32),
        }
        build_segment(schema, cols, out, cfg, f"s{i}")


def build_blockskip():
    """10M-row time-ordered table for the zone-map selectivity sweep: ``ts``
    ascends globally (time-ordered ingestion — the layout Pinot's sorted
    column + our zone maps both exploit), so a ts range of selectivity s
    touches ~s of the blocks. ``ts`` is RAW (no_dictionary) to exercise the
    raw-space zone verdicts."""
    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import IndexingConfig, TableConfig
    from pinot_tpu.storage.creator import build_segment

    out_base = os.path.join(CACHE, "bskip")
    if _built(out_base, BSKIP_SEGMENTS):
        return
    schema = Schema.build(
        name="bskip",
        dimensions=[("ts", DataType.LONG)],
        metrics=[("val", DataType.INT)],
    )
    cfg = TableConfig(
        table_name="bskip",
        indexing=IndexingConfig(no_dictionary_columns=["ts"]),
    )
    rng = np.random.default_rng(13)
    for i in range(BSKIP_SEGMENTS):
        out = os.path.join(out_base, f"s{i}")
        if os.path.exists(os.path.join(out, "metadata.json")):
            continue
        n = BSKIP_ROWS
        cols = {
            "ts": (np.int64(i) * n + np.arange(n, dtype=np.int64)),
            "val": rng.integers(0, 10_000, n).astype(np.int32),
        }
        build_segment(schema, cols, out, cfg, f"s{i}")


def bench_blockskip(engine):
    """Selectivity sweep for the zone-map block-skip path: a ts range at
    selectivity s ∈ {1e-4, 1e-2, 0.5} on the 10M-row time-ordered table,
    default engine vs SET useBlockSkip=false (force-dense). Reports p50
    for both, the entries-scanned ratio, and blocks pruned — the ISSUE-4
    acceptance numbers (>=3x p50 and >=100x scanned at 1e-4; <5% dense
    regression at 0.5, where the static candidate bound overflows and the
    in-kernel dense fallback engages). Differential parity is asserted,
    not sampled."""
    total = BSKIP_SEGMENTS * BSKIP_ROWS
    out = {}
    for label, sel in (("1e-4", 1e-4), ("1e-2", 1e-2), ("0.5", 0.5)):
        window = max(1, int(total * sel))
        lo = total // 3
        hi = lo + window - 1
        sql = (f"SELECT COUNT(*), SUM(val) FROM bskip "
               f"WHERE ts BETWEEN {lo} AND {hi}")
        dense_sql = "SET useBlockSkip = false; " + sql
        r_skip = engine.execute(sql)
        r_dense = engine.execute(dense_sql)
        if r_skip.get("exceptions") or r_dense.get("exceptions"):
            raise RuntimeError((r_skip, r_dense))
        if r_skip["resultTable"]["rows"] != r_dense["resultTable"]["rows"]:
            raise SystemExit(
                f"blockskip differential mismatch at sel={label}: "
                f"{r_skip['resultTable']['rows']} vs "
                f"{r_dense['resultTable']['rows']}")
        lat = run_samples(engine, sql, 7)
        lat_dense = run_samples(engine, dense_sql, 7)
        p50 = float(np.percentile(lat, 50))
        p50_dense = float(np.percentile(lat_dense, 50))
        scanned = r_skip["numEntriesScannedInFilter"]
        scanned_dense = r_dense["numEntriesScannedInFilter"]
        out[f"sel_{label}"] = {
            "p50_ms": round(p50 * 1e3, 2),
            "dense_p50_ms": round(p50_dense * 1e3, 2),
            "speedup_vs_dense": round(p50_dense / p50, 2) if p50 > 0 else None,
            "entries_scanned": scanned,
            "dense_entries_scanned": scanned_dense,
            "scan_ratio": round(scanned_dense / scanned, 1)
            if scanned else None,
            "blocks_pruned": r_skip["numBlocksPruned"],
        }
    return out


def bench_narrow(engine, taxi_segs):
    """ISSUE-5 narrow-residency detail: resident HBM bytes of the taxi
    batch's dict-heavy query columns at their PLANNED widths vs the r05
    wide layout (PINOT_TPU_FORCE_WIDE=1), upload/materialization time
    both ways, and the PR-4 block-skip selectivity sweep re-run on a
    forced-wide engine so scan p50 narrow-vs-wide is a same-dataset,
    same-plan comparison. Query parity narrow-vs-wide is asserted, not
    sampled; the executor's HBM/LRU counters ride along."""
    from pinot_tpu.engine.engine import QueryEngine
    from pinot_tpu.engine.params import BatchContext

    cols = ("zone", "hour", "vendor", "fare")  # the suite's dict planes

    t0 = time.perf_counter()
    ctx_n = BatchContext(taxi_segs)
    for c in cols:
        ctx_n.column(c)
    upload_narrow_s = time.perf_counter() - t0

    # narrow-engine parity rows run BEFORE the forced-wide window: a
    # batch_for rebuild inside it (byte-budget evictions are routine in
    # this bench) would silently cache a WIDE batch under the narrow
    # engine and turn the sweep below into wide-vs-wide
    parity_sqls = ("SELECT COUNT(*), SUM(val) FROM bskip "
                   "WHERE ts BETWEEN 3000000 AND 3499999",
                   "SELECT COUNT(*), MIN(val), MAX(val) FROM bskip "
                   "WHERE ts < 50000")
    rows_narrow = [engine.execute(sql)["resultTable"]["rows"]
                   for sql in parity_sqls]

    prior_fw = os.environ.get("PINOT_TPU_FORCE_WIDE")
    os.environ["PINOT_TPU_FORCE_WIDE"] = "1"
    try:
        t0 = time.perf_counter()
        ctx_w = BatchContext(taxi_segs)
        for c in cols:
            ctx_w.column(c)
        upload_wide_s = time.perf_counter() - t0
        wide_eng = QueryEngine()
        for s in engine.tables["bskip"].segments.values():
            wide_eng.add_segment("bskip", s)
        # parity: wide engine answers == narrow engine answers (each
        # sweep run also asserts skip == dense internally)
        for sql, rn in zip(parity_sqls, rows_narrow):
            rw = wide_eng.execute(sql)
            if rn != rw["resultTable"]["rows"]:
                raise SystemExit(
                    f"narrow vs wide mismatch: {sql}: "
                    f"{rn} vs {rw['resultTable']['rows']}")
        sweep_wide = bench_blockskip(wide_eng)
        wide_eng = None  # release the wide bskip batch's HBM pre-sweep
    finally:
        # RESTORE, don't delete: a whole-bench forced-wide run
        # (PINOT_TPU_FORCE_WIDE=1 python bench.py) must stay wide for the
        # phases after this one
        if prior_fw is None:
            os.environ.pop("PINOT_TPU_FORCE_WIDE", None)
        else:
            os.environ["PINOT_TPU_FORCE_WIDE"] = prior_fw

    nb, wb = ctx_n.device_bytes(), ctx_w.device_bytes()
    saved = ctx_n.narrow_saved_bytes()
    plans = {c: str(np.dtype(ctx_n.width_plan(c).dtype).name) for c in cols}
    # the measurement contexts live OUTSIDE the executor's byte budget —
    # drop both before the sweeps so peak HBM stays bounded
    ctx_n = ctx_w = None
    sweep_narrow = bench_blockskip(engine)
    out = {
        "columns": list(cols),
        "width_plan": plans,
        "resident_bytes_narrow": nb,
        "resident_bytes_wide": wb,
        "shrink_ratio": round(wb / nb, 2) if nb else None,
        "narrow_saved_bytes": saved,
        "upload_narrow_s": round(upload_narrow_s, 3),
        "upload_wide_s": round(upload_wide_s, 3),
        "hbm": engine.device.hbm_stats() if engine.device else None,
        "sweep": {},
    }
    if out["hbm"] is not None:
        out["hbm"].pop("batches", None)  # keep the JSON line compact
    for sel in sweep_narrow:
        n_p50 = sweep_narrow[sel]["p50_ms"]
        w_p50 = sweep_wide[sel]["p50_ms"]
        out["sweep"][sel] = {
            "p50_ms": n_p50,
            "wide_p50_ms": w_p50,
            "p50_ratio_vs_wide": round(n_p50 / w_p50, 3) if w_p50 else None,
        }
    return out


TAXI_QUERIES = {
    "range_sum": "SELECT SUM(fare) FROM bench WHERE fare BETWEEN 1000 AND 5000",
    "groupby": (
        "SET useStarTree = false; "
        "SELECT zone, hour, COUNT(*), SUM(fare), AVG(distance) FROM bench "
        "GROUP BY zone, hour ORDER BY SUM(fare) DESC, zone, hour LIMIT 10"
    ),
    "startree_groupby": (
        "SELECT zone, hour, COUNT(*), SUM(fare) FROM bench "
        "GROUP BY zone, hour ORDER BY SUM(fare) DESC, zone, hour LIMIT 10"
    ),
    "in_filter": (
        "SELECT COUNT(*), SUM(fare) FROM bench WHERE "
        "vendor IN ('v1','v3','v5') AND hour BETWEEN 7 AND 10"
    ),
    "hll": (
        "SELECT vendor, COUNT(*), DISTINCTCOUNTHLL(zone) FROM bench "
        "GROUP BY vendor ORDER BY vendor"
    ),
}

SSB_QUERIES = {
    # 1. baseballStats shape: full scan-agg group-by
    "q1_scan_agg": (
        "SET useStarTree = false; "
        "SELECT lo_suppkey, SUM(lo_revenue) FROM lineorder "
        "GROUP BY lo_suppkey ORDER BY SUM(lo_revenue) DESC LIMIT 10"
    ),
    # 2. SSB Q1.x shape: date range + discount/quantity bands
    "q2_range_sum": (
        "SELECT SUM(lo_revenue) FROM lineorder WHERE "
        "lo_orderdate BETWEEN 19930101 AND 19931231 "
        "AND lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25"
    ),
    # 3. inverted-index shape: IN + range
    "q3_in_range": (
        "SELECT COUNT(*), SUM(lo_revenue) FROM lineorder WHERE "
        "lo_suppkey IN (11, 234, 567, 890, 1203, 1456, 1789) "
        "AND lo_discount BETWEEN 4 AND 6"
    ),
    # 4. NYC-taxi shape: high-cardinality group-by + HLL (cube-eligible:
    # the lo_suppkey star-tree pre-aggregates COUNT/SUM/HLL planes)
    # lo_suppkey tiebreaker: groups tied on COUNT(*) at the LIMIT boundary
    # must order identically on the cube and scan plans or the exactness
    # gate below flakes on tied data
    "q4_highcard_hll": (
        "SELECT lo_suppkey, COUNT(*), AVG(lo_quantity), "
        "DISTINCTCOUNTHLL(lo_custkey) FROM lineorder "
        "GROUP BY lo_suppkey ORDER BY COUNT(*) DESC, lo_suppkey LIMIT 10"
    ),
    # 4b. the same shape forced off the cube: DEFAULT engine behavior,
    # which lazily builds a sorted (group, hash) projection on first use
    # (BatchContext.sorted_hll_keys) and reuses it — steady state pays
    # boundaries + one matmul, not the sort
    "q4_scan_hll": (
        "SET useStarTree = false; "
        "SELECT lo_suppkey, COUNT(*), AVG(lo_quantity), "
        "DISTINCTCOUNTHLL(lo_custkey) FROM lineorder "
        "GROUP BY lo_suppkey ORDER BY COUNT(*) DESC, lo_suppkey LIMIT 10"
    ),
    # 4c. the COLD frontier: no cube AND no cached projection — every
    # query pays the full sort (the conservative number the headline uses)
    "q4_scan_hll_cold": (
        "SET useStarTree = false; SET useSortedProjection = false; "
        "SELECT lo_suppkey, COUNT(*), AVG(lo_quantity), "
        "DISTINCTCOUNTHLL(lo_custkey) FROM lineorder "
        "GROUP BY lo_suppkey ORDER BY COUNT(*) DESC, lo_suppkey LIMIT 10"
    ),
    # 5. SSB Q4.x shape: star-tree 3-dim pre-aggregated group-by
    "q5_startree": (
        "SELECT d_year, c_region, SUM(lo_revenue), COUNT(*) FROM lineorder "
        "GROUP BY d_year, c_region ORDER BY d_year, c_region LIMIT 50"
    ),
}


def smoke_gate():
    """Tiny REAL-backend compile+run of every Pallas path before the 100M
    suite: a Mosaic layout/padding regression must die here with a clear
    message, not as a 50GB allocation two minutes into the bench.
    (Round-2 postmortem: interpret-mode tests can't see TPU layout
    blowups — VERDICT.md round 2, weak #2.)"""
    import jax
    import jax.numpy as jnp

    from pinot_tpu.ops import groupby_mm as mm

    # off-TPU the engine routes to scatter anyway; interpret mode still
    # checks the kernel math without requiring Mosaic lowering
    interp = jax.default_backend() != "tpu"
    rng = np.random.default_rng(3)
    n, G, A = 200_000, 6240, 4
    gid = rng.integers(0, G, n).astype(np.int32)
    vals = rng.integers(0, 255, (A, n)).astype(np.float32)
    out = np.asarray(
        jax.device_get(
            jax.jit(lambda g, c: mm.group_sums(g, c, G, interpret=interp))(
                jnp.asarray(gid), jnp.asarray(vals).astype(jnp.bfloat16)
            )
        )
    )
    ref = np.zeros((A, G))
    for a in range(A):
        np.add.at(ref[a], gid, vals[a])
    if np.abs(out - ref).max() != 0:
        raise SystemExit("smoke_gate: group_sums kernel mismatch on real backend")

    log2m, ngr = 10, 8
    m = 1 << log2m
    slot = rng.integers(0, ngr * m, n).astype(np.int32)
    rho = rng.integers(1, 23, n).astype(np.int32)
    regs = np.asarray(
        jax.device_get(
            jax.jit(lambda s, r: mm.hll_registers(s, r, ngr, log2m,
                                                  interpret=interp))(
                jnp.asarray(slot), jnp.asarray(rho)
            )
        )
    )
    ref_regs = np.zeros(ngr * m, dtype=np.int32)
    np.maximum.at(ref_regs, slot, rho)
    if np.abs(regs.reshape(-1) - ref_regs).max() != 0:
        raise SystemExit("smoke_gate: hll_registers kernel mismatch on real backend")
    print(f"smoke_gate OK on {jax.default_backend()}", file=sys.stderr)


def run_samples(engine, sql, iters):
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        resp = engine.execute(sql)
        lat.append(time.perf_counter() - t0)
        if resp.get("exceptions"):
            raise RuntimeError(resp["exceptions"])
    return lat


def measure_link_floor():
    """Round-trip floor of the host<->device link: a trivial dispatch +
    fetch. EVERY query pays at least this much end-to-end — on a tunneled
    chip it dominates (measured ~100ms vs ~0.1ms PCIe-local), so the
    per-query breakdown reports it separately from engine work."""
    import jax
    import jax.numpy as jnp

    x = jnp.zeros((8,), jnp.int32)
    f = jax.jit(lambda v: v + 1)
    jax.device_get(f(x))
    samples = []
    for _ in range(5):
        t0 = time.perf_counter()
        jax.device_get(f(x))
        samples.append(time.perf_counter() - t0)
    return float(min(samples))


HBM_PEAK_GBPS = 819.0  # v5e chip HBM bandwidth


def bench_suite(engine, queries, warm=2, iters=7):
    """Per query: end-to-end p50/p99 PLUS a measured three-way breakdown —
    kernel_ms (amortized repeated-launch device time,
    DeviceExecutor.profile_last_launch), host_ms (wall minus the blocking
    device_get wait — measured, not floor-subtracted: the tunnel's RTT
    variance above its floor is link, not engine), link_ms (median of the
    SAME per-iteration get-wait samples minus kernel, clamped at 0 — the
    old p50 - kernel - host arithmetic mixed medians of different sample
    sets and went negative on short queries), and effective GB/s of
    device-resident bytes the kernel
    read vs HBM peak (VERDICT r4 #1: hardware efficiency must be a
    measured number)."""
    detail = {}
    dev = engine.device
    if dev is not None:
        dev.profile_enabled = True  # opt-in launch capture (bench only)
    for name, sql in queries.items():
        run_samples(engine, sql, warm)
        b0 = (dev.fetch_bytes_total, dev.fetch_leaves_total) if dev else (0, 0)
        if dev is not None:
            # a query answered WITHOUT a device launch (metadata-only,
            # host fallback) must not inherit the previous query's profile
            dev._last_launch = None
            dev.last_get_wait_s = None
        host_samples = []
        get_samples = []
        lat = []
        for _ in range(iters):
            if dev is not None:
                dev.last_get_wait_s = None
            t0 = time.perf_counter()
            resp = engine.execute(sql)
            wall = time.perf_counter() - t0
            lat.append(wall)
            if resp.get("exceptions"):
                raise RuntimeError(resp["exceptions"])
            get_wait = getattr(dev, "last_get_wait_s", None) if dev else None
            if get_wait is not None:
                host_samples.append(max(0.0, wall - get_wait))
                get_samples.append(get_wait)
        entry = {}
        if dev is not None and dev.fetch_bytes_total > b0[0]:
            entry["fetch_kb_per_query"] = round(
                (dev.fetch_bytes_total - b0[0]) / iters / 1024, 1)
            entry["fetch_leaves_per_query"] = round(
                (dev.fetch_leaves_total - b0[1]) / iters, 1)
        # the metric is STEADY-STATE latency: drop at most one sample when
        # it dwarfs the median (transient remote-compile / HBM-relayout
        # hiccup), and say so in the artifact rather than silently
        # re-rolling the whole window
        med = float(np.median(lat))
        if max(lat) > 10 * med and len(lat) >= 5:
            entry["outlier_dropped_ms"] = round(max(lat) * 1e3, 2)
            lat.remove(max(lat))
        entry["p50_ms"] = round(float(np.percentile(lat, 50)) * 1e3, 2)
        entry["p99_ms"] = round(float(np.percentile(lat, 99)) * 1e3, 2)
        prof = dev.profile_last_launch(6) if dev is not None else None
        if prof is not None:
            kernel_s, bytes_in = prof
            entry["kernel_ms"] = round(kernel_s * 1e3, 2)
            entry["host_ms"] = round(
                float(np.median(host_samples)) * 1e3, 2) if host_samples else None
            # link = blocking get-wait minus kernel, from the SAME
            # per-iteration samples host_ms uses; clamp at 0 so RTT
            # jitter on short queries can't report a negative component
            entry["link_ms"] = round(
                max(0.0, float(np.median(get_samples)) * 1e3
                    - entry["kernel_ms"]), 2) if get_samples else None
            entry["device_bytes_read_gb"] = round(bytes_in / 1e9, 2)
            if kernel_s > 5e-4:  # sub-0.5ms kernels: amortized diff ≈ noise
                gbps = bytes_in / kernel_s / 1e9
                entry["kernel_gbps"] = round(gbps, 1)
                entry["hbm_peak_pct"] = round(100 * gbps / HBM_PEAK_GBPS, 1)
        detail[name] = entry
    return detail


def bench_micro():
    """Per-kernel microbenches (the JMH-suite analog, SURVEY §4 /
    pinot-perf/.../BenchmarkScanDocIdIterators.java role): standalone
    rows/s + GB/s per hot kernel, amortized repeated-launch timing with a
    token fetch (block_until_ready is a no-op over the tunnel). Inputs are
    SYNTHESIZED ON DEVICE (iota + avalanche hash) — nothing crosses the
    host link, so the numbers are pure kernel."""
    import jax
    import jax.numpy as jnp

    from pinot_tpu.ops import agg as agg_ops
    from pinot_tpu.ops import groupby_mm as mm
    from pinot_tpu.ops import hll as hll_ops

    N = 100_000_000
    G = 2_000
    LOG2M = 10

    from pinot_tpu.engine.device import amortized_launch_time

    def devtime(f, *args, iters=4):
        g = jax.jit(f)
        tok = jax.jit(lambda o: jnp.sum(
            jax.tree.leaves(o)[0].reshape(-1)[:1].astype(jnp.float32)))

        def timed(k):
            t0 = time.perf_counter()
            o = None
            for _ in range(k):
                o = g(*args)
            jax.device_get(tok(o))
            return time.perf_counter() - t0

        return max(1e-9, amortized_launch_time(timed, base_iters=iters))

    def synth(_):
        i = jnp.arange(N, dtype=jnp.int32)
        h = hll_ops.hash32(i)
        gid = (h % G).astype(jnp.int32)
        v = (h & 0xFFFF).astype(jnp.int32)
        return gid, v, h

    gid, v, h = jax.jit(synth)(0)
    jax.device_get(jnp.sum(gid[:1]))

    out = {}

    def rec(name, secs, bytes_in):
        out[name] = {
            "ms": round(secs * 1e3, 2),
            "mrows_per_s": round(N / secs / 1e6, 1),
            "gbps": round(bytes_in / secs / 1e9, 1),
        }

    # filter-mask + popcount: 3 range predicates over 2 int32 columns
    rec("filter_mask", devtime(
        lambda g, x: jnp.sum((x > 1000) & (x < 60000) & (g != 7),
                             dtype=jnp.int64), gid, v), 8 * N)
    # masked select + exact int64 sum (the scalar-agg shape); reads ONE
    # int32 array (the mask derives from the same column)
    rec("masked_sum", devtime(
        lambda g, x: agg_ops.agg_sum(x, (x & 1) == 0), gid, v), 4 * N)
    # dense scatter-add group sum (the non-MXU fallback)
    rec("scatter_group_sum", devtime(
        lambda g, x: agg_ops.group_sum(g, x, G), gid, v), 8 * N)
    # one-hot matmul group-by, 4 bf16 channels (count + 3 byte planes) —
    # first_channel_ones matches the production call (_try_mm_groupby),
    # which folds the count channel into the hi one-hot
    def mm4(g, x):
        chans = jnp.stack(
            [jnp.ones(N, jnp.bfloat16)] + mm.int_planes(x, jnp.int64(0), 3))
        return mm.group_sums(g, chans, G, first_channel_ones=True)
    rec("mm_groupby_4ch", devtime(mm4, gid, v, iters=3), 8 * N)
    # HLL register scatter-max at the q4 shape (G*m slots)
    m = 1 << LOG2M
    def hllsc(g, hh):
        idx, rho = hll_ops.hll_idx_rho(hh, LOG2M)
        slot = g * m + idx
        return jnp.zeros(G * m + 1, jnp.float32).at[slot].max(
            rho.astype(jnp.float32))
    rec("hll_register_scatter", devtime(hllsc, gid, h, iters=3), 8 * N)
    # sorted register-free HLL build (the terminal q4 path)
    from pinot_tpu.engine.device import _hll_sorted_sums
    def hllsort(g, hh):
        idx, rho = hll_ops.hll_idx_rho(hh, LOG2M)
        slot = g * m + idx
        return _hll_sorted_sums(slot, rho, G, LOG2M, "auto")
    rec("hll_sorted_sums", devtime(hllsort, gid, h, iters=3), 8 * N)
    # sort-based high-cardinality group-by key sort (the RETIRED monolithic
    # basis — kept as the baseline the radix micros are judged against)
    key = jax.jit(lambda g, x: (g.astype(jnp.int64) << 20)
                  | x.astype(jnp.int64))(gid, v)
    jax.device_get(jnp.sum(key[:1]))
    rec("sortkey_int64", devtime(lambda k: jax.lax.sort(k), key, iters=3),
        8 * N)

    # radix-partitioned group-by primitives (ops/radix_groupby.py — the
    # basis that replaced the monolithic sort above). Key space ~100k
    # distinct over 100M rows: the q4 high-cardinality scan shape. The
    # packed key is int32 (pack_keys narrows when the cartesian space
    # fits), so the comparator passes move half the bytes.
    from pinot_tpu.engine.device import MAX_SORTED_GROUPS
    from pinot_tpu.ops import radix_groupby as radix_ops

    HC = 100_000  # distinct-key target (fits MAX_SORTED_GROUPS = 1<<17)
    key32 = jax.jit(lambda hh: radix_ops.pack_keys(
        [(hh % HC).astype(jnp.int32)], (HC,),
        jnp.ones(N, dtype=bool)))(h)
    v64 = jax.jit(lambda x: x.astype(jnp.int64))(v)
    jax.device_get(jnp.sum(key32[:1]))
    # occupancy probe: radix histogram of the key's high bits via the
    # factored one-hot matmul kernel (folded count channel)
    rec("radix_bucket_histogram", devtime(
        lambda k: radix_ops.bucket_histogram(k, HC, 1024), key32, iters=3),
        4 * N)
    # the full chunked aggregate: level-1 chunk sorts + run-end partials +
    # compacted merge, COUNT + int SUM payload riding along
    def radix_agg(k, x):
        return radix_ops.chunked_group_aggregate(
            k, {"p0": (x, "int")}, {"p0"}, set(), set(), MAX_SORTED_GROUPS)
    rec("radix_groupby_chunked", devtime(radix_agg, key32, v64, iters=3),
        12 * N)

    # zone-map block-skip compaction + gather (ops/blockskip.py): verdict
    # over N/4096 blocks -> static-bound candidate compaction -> block
    # gather -> masked count. Rate is rows COVERED per second (the dense
    # scan this replaces would read all N rows); the kernel itself touches
    # only the gathered candidate blocks.
    from pinot_tpu.ops import blockskip as bs_ops

    R_BS = bs_ops.BLOCK_ROWS
    n_bs = (N // R_BS) * R_BS
    nb_bs = n_bs // R_BS

    def bskip_compact(x):
        verdict = (jnp.arange(nb_bs, dtype=jnp.int32) & 63) == 0  # ~1.6%
        bound = max(1, nb_bs // bs_ops.CAND_FRACTION)
        cand, valid = bs_ops.compact_candidates(verdict, bound)
        g = x[:n_bs].reshape(nb_bs, R_BS)[cand]
        return jnp.sum(jnp.where(valid[:, None], g, 0), dtype=jnp.int64)
    rec("blockskip_compact", devtime(bskip_compact, v, iters=3), 4 * N)

    # in-kernel sub-byte unpack (ISSUE 5 narrow tier): 4-bit dict ids
    # packed 2/byte, unpacked with shifts/masks and consumed by an EQ
    # mask + popcount — the device face of FixedBitSVForwardIndexReader.
    # Rate is LOGICAL ids/s; the kernel reads N/2 bytes
    from pinot_tpu.ops.masks import unpack_subbyte

    packed_nu = jax.jit(lambda x: (x[: N // 2] & 0xFF).astype(jnp.uint8))(h)
    jax.device_get(jnp.sum(packed_nu[:1]))
    rec("narrow_unpack", devtime(
        lambda p: jnp.sum(unpack_subbyte(p, 4) == 3, dtype=jnp.int64),
        packed_nu), N // 2)

    # ---- Pallas scatter tier (ISSUE 15, ops/pallas_scatter.py) -----------
    # the purpose-built replacements for the serialized XLA scatters
    # above; each micro runs at the shape its scatter reference ran, so
    # the tier's >=10x acceptance reads straight off this table
    from pinot_tpu.ops import pallas_scatter as ps

    # tiled local-accumulate group scatter at the scatter_group_sum shape
    # (G=2000; count channel folded + 2 int byte planes)
    def pallas_gs(g, x):
        chans = jnp.stack(
            [jnp.ones(N, jnp.bfloat16)]
            + mm.int_planes(x.astype(jnp.int64), jnp.int64(0), 2))
        return ps.plane_group_sums(g, chans, G, first_channel_ones=True)
    rec("pallas_group_scatter", devtime(pallas_gs, gid, v, iters=3), 8 * N)

    # HLL register-max scatter at the scalar-HLL shape (m = 1024 slots —
    # the kernel's regime; group-by register spaces past
    # ps.HLL_MAX_SLOTS stay on the sorted dedup basis)
    def pallas_hll(hh):
        idx, rho = hll_ops.hll_idx_rho(hh, LOG2M)
        return ps.hll_register_max(idx, rho, m, 33 - LOG2M)
    rec("pallas_hll_max", devtime(pallas_hll, h, iters=3), 4 * N)

    # fused filter+gather+aggregate over a ~1.6% candidate block set:
    # scalar-prefetched indices drive the DMA, so no (B, R) gather
    # buffer ever hits HBM. Rate is rows COVERED per second (the dense
    # scan this replaces reads all N rows), like blockskip_compact.
    R_F = ps.FUSED_BLOCK_ROWS
    nb_f = (N // R_F)
    bound_f = max(1, nb_f // bs_ops.CAND_FRACTION)
    fwidths = {"c": ("uint16", 0, False, None)}
    fplan = ps.plan_fused(
        ("range_raw", ("raw", "c"), "plo", "phi", True, True, True, True),
        (("count", None, None), ("sum", ("raw", "c"), (2, 1 << 20))),
        fwidths)
    assert fplan is not None
    x16 = jax.jit(lambda x: (x[: nb_f * R_F] & 0xFFFF).astype(jnp.uint16)
                  .reshape(nb_f, R_F // 128, 128))(v)
    cand_f = jax.jit(lambda _: (
        jnp.arange(bound_f, dtype=jnp.int32) * bs_ops.CAND_FRACTION) % nb_f)(0)
    rows_f = jax.jit(lambda _: jnp.full(bound_f, R_F, jnp.int32))(0)
    jax.device_get(jnp.sum(x16[:1, :1, :1]))

    def pallas_fused(xc, cd, rw):
        return ps.fused_filter_agg(
            cd, rw, {"c": xc},
            {"plo": jnp.array([100], jnp.int32),
             "phi": jnp.array([60000], jnp.int32)}, fplan)[0]
    rec("pallas_fused_filter_agg",
        devtime(pallas_fused, x16, cand_f, rows_f, iters=3), 2 * N)

    # on-device final reduce: sort-based ORDER BY trim over a group table
    # (ops/device_reduce.py — the kernel that replaced the host
    # BrokerReduceService walk + full-table fetch)
    out["device_trim_topk"] = _trim_topk_micro()

    # bit-unpack: host C++ forward-index decode (native/packer.cpp)
    try:
        from pinot_tpu import native as native_bitpack

        rng = np.random.default_rng(0)
        n_un = 20_000_000
        vals = rng.integers(0, 1 << 17, n_un).astype(np.int32)
        packed = native_bitpack.pack(vals, 17)
        t0 = time.perf_counter()
        unpacked = native_bitpack.unpack(packed, n_un, 17)
        dt = time.perf_counter() - t0
        assert np.array_equal(unpacked, vals)
        out["bit_unpack_cpp"] = {
            "ms": round(dt * 1e3, 2),
            "mrows_per_s": round(n_un / dt / 1e6, 1),
            "gbps": round(4 * n_un / dt / 1e9, 1),  # decoded bytes out
        }
    except Exception as e:  # noqa: BLE001 — optional native path
        out["bit_unpack_cpp"] = {"error": f"{type(e).__name__}: {e}"}
    return out


def _trim_topk_micro(G: int = 4_000_000, K: int = 8192):
    """device_trim_topk micro: the on-device final reduce's core — sort a
    G-row group table by (present, key desc, slot) and gather the top-K
    rows (ops/device_reduce.py apply_trim shape). Inputs synthesized on
    device; rate is table rows per second."""
    import jax
    import jax.numpy as jnp

    from pinot_tpu.engine.device import amortized_launch_time
    from pinot_tpu.ops import hll as hll_ops

    def synth(_):
        i = jnp.arange(G, dtype=jnp.int32)
        h = hll_ops.hash32(i)
        counts = (h & 0xFFFF).astype(jnp.int64)
        sums = (h >> 3).astype(jnp.float64)
        return counts, sums

    counts, sums = jax.jit(synth)(0)
    jax.device_get(jnp.sum(counts[:1]))

    def trim(c, s):
        ops = (jnp.where(c > 0, jnp.int32(0), jnp.int32(1)),
               -c, jnp.arange(G, dtype=jnp.int64))
        srt = jax.lax.sort(ops, num_keys=3)
        perm = srt[2][:K]
        return c[perm], s[perm]

    g = jax.jit(trim)

    def timed(k):
        o = None
        t0 = time.perf_counter()
        for _ in range(k):
            o = g(counts, sums)
        jax.device_get(jnp.sum(o[0][:1].astype(jnp.float32)))
        return time.perf_counter() - t0

    secs = max(1e-9, amortized_launch_time(timed, base_iters=3))
    return {
        "ms": round(secs * 1e3, 2),
        "mrows_per_s": round(G / secs / 1e6, 1),
        "gbps": round(16 * G / secs / 1e9, 1),  # int64 key + f64 payload
    }


def bench_concurrency(engine, sql, levels=(1, 4, 8), iters_per_thread=4):
    """Link-amortization sweep (the tentpole metric of the async
    launch/fetch split): N threads submit the same query concurrently
    through ONE engine. Per level: aggregate qps + per-query p50, and
    ``overlap_efficiency`` = N·qps₁/qps_N (1.0 = the N round trips fully
    overlap; N = they serialize — each query pays its own RTT as the old
    blocking device_get did). ``coalesced_cohort_p50_ms``: 8
    identical-template queries released together (the dashboard fan-out
    case) — the coalescer stacks them into ONE vmapped launch fetched as
    ONE packed buffer, so the target is p50 ≤ 1.5× the solo p50."""
    import threading

    def run_level(n, iters):
        barrier = threading.Barrier(n + 1)
        lats = [[] for _ in range(n)]
        errs = []

        def worker(i):
            try:
                barrier.wait()
                for _ in range(iters):
                    t0 = time.perf_counter()
                    r = engine.execute(sql)
                    lats[i].append(time.perf_counter() - t0)
                    if r.get("exceptions"):
                        errs.append(str(r["exceptions"])[:200])
                        return
            except Exception as e:  # noqa: BLE001 — surfaced after join
                errs.append(repr(e))

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(n)]
        for t in ts:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in ts:
            t.join()
        wall = time.perf_counter() - t0
        if errs:
            raise RuntimeError(f"concurrency sweep failed: {errs[0]}")
        return wall, [x for lat in lats for x in lat]

    dev = engine.device
    cache_was = None
    if dev is not None:
        # profile capture pins launches and disables coalescing — the
        # sweep must measure the production execute path. The partials
        # cache is disabled for the sweep: this detail measures the
        # launch/fetch OVERLAP machinery (comparable across rounds);
        # cache-hot steady-state QPS is detail.subrtt's metric.
        dev.profile_enabled = False
        cache_was = dev.partials_cache_enabled
        dev.partials_cache_enabled = False
    run_level(1, 2)  # warm (compile + batch caches)
    out = {}
    qps1 = None
    for n in levels:
        # warm pass at this concurrency: cohort pipelines jit-specialize
        # per pow2-padded cohort size, and steady-state amortization (not
        # first-compile) is the metric
        run_level(n, 1)
        wall, lat = run_level(n, iters_per_thread)
        qps = len(lat) / wall
        entry = {
            "qps": round(qps, 2),
            "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
        }
        if n == 1:
            qps1 = qps
        elif qps1 is not None:  # relative fields need a level-1 reference
            entry["speedup_vs_n1"] = round(qps / qps1, 2)
            entry["overlap_efficiency"] = round(n * qps1 / qps, 2)
        out[f"n{n}"] = entry
    co = getattr(dev, "coalescer", None) if dev is not None else None
    c0 = (co.cohorts_launched, co.queries_coalesced, co.stream_windows) \
        if co else (0, 0, 0)
    _, lat = run_level(8, 1)
    out["coalesced_cohort_p50_ms"] = round(
        float(np.percentile(lat, 50)) * 1e3, 2)
    if co is not None:
        out["cohorts_launched"] = co.cohorts_launched - c0[0]
        out["queries_coalesced"] = co.queries_coalesced - c0[1]
        out["stream_windows"] = co.stream_windows - c0[2]
    if dev is not None and cache_was is not None:
        dev.partials_cache_enabled = cache_was
    return out


def bench_realtime():
    """Realtime path numbers (BenchmarkRealtimeConsumptionSpeed analog):
    row-at-a-time ingest rate into a consuming (mutable) segment, seal
    time, and query latency OVER the consuming segment (host scan path —
    the reference serves CONSUMING segments as a first-class path)."""
    import shutil

    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.engine.engine import QueryEngine
    from pinot_tpu.storage.mutable import MutableSegment

    schema = Schema.build(
        name="rt",
        dimensions=[("zone", DataType.STRING), ("hour", DataType.INT)],
        metrics=[("fare", DataType.INT)],
    )
    rng = np.random.default_rng(4)
    n = 200_000
    zones = [f"zone_{i:03d}" for i in range(260)]
    rows = [
        {"zone": zones[z], "hour": int(h), "fare": int(f)}
        for z, h, f in zip(
            rng.integers(0, 260, n), rng.integers(0, 24, n),
            rng.integers(100, 10_000, n),
        )
    ]
    seg = MutableSegment(schema, "rt__0__0__0")
    t0 = time.perf_counter()
    for r in rows:
        seg.index(r)
    ingest_s = time.perf_counter() - t0

    # columnar batch path (chunklet ingest basis) on identical rows
    seg_b = MutableSegment(schema, "rt__0__0__1")
    t0 = time.perf_counter()
    for i in range(0, n, 8192):
        seg_b.index_batch(rows[i:i + 8192])
    batch_ingest_s = time.perf_counter() - t0

    eng = QueryEngine(device_executor=None)
    eng.add_segment("rt", seg)
    sql = ("SELECT zone, COUNT(*), SUM(fare) FROM rt GROUP BY zone "
           "ORDER BY SUM(fare) DESC LIMIT 10")
    lat = run_samples(eng, sql, 5)

    out = os.path.join(CACHE, "rt_sealed")
    shutil.rmtree(out, ignore_errors=True)
    t0 = time.perf_counter()
    seg.seal(out)
    seal_s = time.perf_counter() - t0
    return {
        "ingest_rows_per_s": round(n / ingest_s),
        "batch_ingest_rows_per_s": round(n / batch_ingest_s),
        "seal_ms": round(seal_s * 1e3, 1),
        "consuming_query_p50_ms": round(
            float(np.percentile(lat, 50)) * 1e3, 2),
        "consuming_rows": n,
        "multi_partition": bench_realtime_multipartition(),
    }


def bench_realtime_multipartition(n_partitions: int = 4,
                                  rows_per_partition: int = 1_000_000):
    """N consuming partitions ingesting IN PARALLEL across OS PROCESSES
    (one consume loop per partition, the controller-HA test's process
    harness — realtime/chunklet.py ingest_worker_main), each running the
    columnar ``index_batch`` path with chunklet promotion. BENCH_r05's
    thread-based version measured 1.007x 'scaling' at 4 partitions: the
    GIL serialized the per-row index path, so partitions never ran in
    parallel at all. Basis matches r05 (pre-decoded rows); the
    decode-inclusive stream variant reports separately.

    Aggregate = total rows / slowest worker's ingest seconds (process
    startup excluded — workers time only their consume phase). While the
    worker processes ingest, the PARENT runs a query loop against its own
    locally-consuming chunklet segment (the old harness's gate, kept: a
    regression that breaks querying during concurrent consumption must
    FAIL the bench, not report null latency)."""
    import subprocess
    import sys
    import threading

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}  # workers must not grab TPU

    def run_workers(payload: str, rows: int, query_probe: bool = False):
        procs = []
        try:
            for p in range(n_partitions):
                spec = json.dumps({
                    "rows": rows, "partition": p, "payload": payload,
                    "rows_per_chunklet": 65_536,
                })
                procs.append(subprocess.Popen(
                    [sys.executable, "-m", "pinot_tpu.realtime.chunklet",
                     spec],
                    stdout=subprocess.PIPE, env=env))
            probe = _query_during_ingest(procs) if query_probe else None
            outs = []
            for p in procs:
                stdout, _ = p.communicate(timeout=600)
                if p.returncode != 0:
                    raise RuntimeError(
                        f"ingest worker failed (rc={p.returncode})")
                outs.append(json.loads(stdout))
        finally:
            # a failed/timed-out phase must not leave sibling workers
            # ingesting in the background under later phases
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=10)
        total = sum(o["rows"] for o in outs)
        out = {
            "aggregate_rows_per_s": round(
                total / max(o["seconds"] for o in outs)),
            "per_partition_rows_per_s": [o["rows_per_s"] for o in outs],
            "rows": total,
            "chunklets": sum(o["chunklets"] for o in outs),
        }
        if probe is not None:
            out.update(probe)
        return out

    def _query_during_ingest(procs):
        """Queries against a locally-consuming chunklet segment while the
        worker processes saturate the machine's cores with ingest."""
        from pinot_tpu.common.datatypes import DataType
        from pinot_tpu.common.schema import Schema
        from pinot_tpu.common.table_config import ChunkletConfig, TableConfig
        from pinot_tpu.engine.engine import QueryEngine
        from pinot_tpu.storage.mutable import MutableSegment

        schema = Schema.build(
            name="rtp",
            dimensions=[("zone", DataType.STRING), ("hour", DataType.INT)],
            metrics=[("fare", DataType.INT)])
        cfg = TableConfig(
            table_name="rtp",
            chunklets=ChunkletConfig(enabled=True, rows_per_chunklet=65_536,
                                     device_min_rows=65_536))
        seg = MutableSegment(schema, "rtp__0__0__0", cfg)
        eng = QueryEngine()
        eng.add_segment("rtp", seg)
        rng = np.random.default_rng(23)
        base = [{"zone": f"zone_{z:03d}", "hour": int(h), "fare": int(f)}
                for z, h, f in zip(rng.integers(0, 260, 8192),
                                   rng.integers(0, 24, 8192),
                                   rng.integers(100, 10_000, 8192))]
        stop = threading.Event()

        def feed():
            while not stop.is_set():
                seg.index_batch(base)
                seg.chunklet_index.promote()
                time.sleep(0.002)

        feeder = threading.Thread(target=feed, daemon=True)
        feeder.start()
        sql = ("SELECT zone, COUNT(*), SUM(fare) FROM rtp GROUP BY zone "
               "ORDER BY SUM(fare) DESC LIMIT 10")
        lats, errors = [], []
        while any(p.poll() is None for p in procs):
            t0 = time.perf_counter()
            try:
                r = eng.execute(sql)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(repr(e))
                break
            if r.get("exceptions"):
                errors.append(str(r["exceptions"])[:200])
                break
            lats.append(time.perf_counter() - t0)
            time.sleep(0.01)
        stop.set()
        feeder.join(5)
        if errors:
            raise RuntimeError(
                f"concurrent query failed during multi-partition ingest: "
                f"{errors[0]}")
        return {
            "concurrent_query_p50_ms": round(
                float(np.percentile(lats, 50)) * 1e3, 2) if lats else None,
            "concurrent_queries_served": len(lats),
        }

    batch = run_workers("rows", rows_per_partition)
    # query-under-ingest gate as its OWN short phase: the parent's query
    # engine contends for cores, so probing the headline run would tax the
    # throughput number on small hosts
    probe_run = run_workers("rows", max(100_000, rows_per_partition // 4),
                            query_probe=True)
    # decode-inclusive: full stream fetch + batched JSON decode per row
    stream = run_workers("json", max(100_000, rows_per_partition // 4))
    return {
        "partitions": n_partitions,
        "aggregate_ingest_rows_per_s": batch["aggregate_rows_per_s"],
        "rows": batch["rows"],
        "per_partition_rows_per_s": batch["per_partition_rows_per_s"],
        "chunklets_promoted": batch["chunklets"],
        "concurrent_query_p50_ms": probe_run.get("concurrent_query_p50_ms"),
        "concurrent_queries_served": probe_run.get(
            "concurrent_queries_served", 0),
        "stream_json_decode": stream,
        "note": ("per-partition OS processes + columnar index_batch "
                 "(chunklet subsystem); basis matches BENCH_r05 "
                 "(pre-decoded rows), stream_json_decode includes fetch + "
                 "batched JSON decode"),
    }


def bench_chunklet():
    """Chunklet subsystem numbers: consuming-segment query p50 vs segment
    size, device-chunklet+host-tail against the equivalent sealed
    immutable segment on the SAME device engine (the acceptance bar:
    consuming p50 at 1M rows <= 2x immutable p50). Crossover is config
    (TableConfig.chunklets.device_min_rows); the bench pins it low so
    both sizes engage the device path."""
    import shutil

    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import ChunkletConfig, TableConfig
    from pinot_tpu.engine.engine import QueryEngine
    from pinot_tpu.realtime.chunklet import split_for_query
    from pinot_tpu.storage.mutable import MutableSegment

    schema = Schema.build(
        name="rtq",
        dimensions=[("zone", DataType.STRING), ("hour", DataType.INT)],
        metrics=[("fare", DataType.INT)],
    )
    cfg = TableConfig(
        table_name="rtq",
        chunklets=ChunkletConfig(enabled=True, rows_per_chunklet=65_536,
                                 device_min_rows=65_536))
    sql = ("SELECT zone, COUNT(*), SUM(fare) FROM rtq GROUP BY zone "
           "ORDER BY SUM(fare) DESC LIMIT 10")
    rng = np.random.default_rng(17)
    out = {}
    for label, n in (("200k", 200_000), ("1m", 1_000_000)):
        zones = rng.integers(0, 260, n)
        hours = rng.integers(0, 24, n)
        fares = rng.integers(100, 10_000, n)
        rows = [{"zone": f"zone_{z:03d}", "hour": int(h), "fare": int(f)}
                for z, h, f in zip(zones, hours, fares)]
        seg = MutableSegment(schema, f"rtq__{label}", cfg)
        for i in range(0, n, 65_536):
            seg.index_batch(rows[i:i + 65_536])
            seg.chunklet_index.promote()
        split = split_for_query(seg)
        eng = QueryEngine()
        eng.add_segment("rtq", seg)
        run_samples(eng, sql, 2)  # warm: batch upload + template compile
        lat = run_samples(eng, sql, 7)
        consuming_p50 = float(np.percentile(lat, 50))

        sealed_dir = os.path.join(CACHE, f"rtq_sealed_{label}")
        shutil.rmtree(sealed_dir, ignore_errors=True)
        sealed = seg.seal(sealed_dir)
        eng2 = QueryEngine()
        eng2.add_segment("rtq", sealed)
        run_samples(eng2, sql, 2)
        lat2 = run_samples(eng2, sql, 7)
        immutable_p50 = float(np.percentile(lat2, 50))

        host_eng = QueryEngine(device_executor=None)
        host_eng.add_segment("rtq", seg)
        host_lat = run_samples(host_eng, sql, 3)

        # mixed-backend differential: the promoted path must answer
        # exactly like the all-host scan
        if eng.execute(sql)["resultTable"]["rows"] != \
                host_eng.execute(sql)["resultTable"]["rows"]:
            raise SystemExit(
                f"chunklet differential mismatch at {label}")
        out[label] = {
            "rows": n,
            "device_chunklets": len(split[0]) if split else 0,
            "host_tail_rows": (n - seg.chunklet_index.frozen_docs),
            "consuming_p50_ms": round(consuming_p50 * 1e3, 2),
            "immutable_p50_ms": round(immutable_p50 * 1e3, 2),
            "consuming_vs_immutable": round(
                consuming_p50 / immutable_p50, 2),
            "all_host_p50_ms": round(
                float(np.percentile(host_lat, 50)) * 1e3, 2),
        }
    return out


# BENCH_r05 detail.micro reference (mrows_per_s) — the regression gate's
# floor values when BENCH_r05.json is absent or unparseable (its driver
# wrapper only keeps an output tail)
_MICRO_R05_REFERENCE = {
    "filter_mask": 91038.5,
    "masked_sum": 205509.5,
    "scatter_group_sum": 84.9,
    "mm_groupby_4ch": 3281.7,
    "hll_register_scatter": 149.0,
    "hll_sorted_sums": 265.3,
    "sortkey_int64": 198.0,
    "bit_unpack_cpp": 277.6,
    # first recorded round 8 (zone-map block-skip); conservative floor —
    # the kernel reads ~1/16 of the rows it covers, so real rates run far
    # above this (gates only against catastrophic regressions until a
    # recorded BENCH_r08 reference takes over)
    "blockskip_compact": 500.0,
    # first recorded round 9 (narrow-width residency): in-kernel 4-bit
    # unpack + EQ mask reads 0.5 bytes/row — conservative embedded floor
    # until a recorded reference takes over
    "narrow_unpack": 800.0,
    # first recorded round 15 (Pallas scatter tier): embedded floors
    # encode the tier's >=10x acceptance against the r05 scatter
    # references at the SAME shapes (scatter_group_sum 84.9,
    # hll_register_scatter 149.0) until a recorded reference takes over;
    # the fused micro floors at 2x blockskip_compact (it reads the same
    # ~1/16 candidate fraction but skips the gather round trip)
    "pallas_group_scatter": 849.0,
    "pallas_hll_max": 1490.0,
    "pallas_fused_filter_agg": 1000.0,
    # first recorded round 12 (sub-RTT serving): the on-device final
    # reduce's sort-based top-K over a 4M-row group table (3 sort
    # operands + trimmed gather). Conservative embedded floor — a 2-core
    # CPU box runs ~3x it, a TPU far above — until a recorded reference
    # takes over
    "device_trim_topk": 0.5,
}


def process_scaling_ceiling() -> float:
    """What 2 pinned CPU-bound OS processes can achieve on THIS box
    relative to 2x one process — the environment's own hard cap on
    any 2-server scaling figure. On a real multi-core host this is
    ~1.0 and the normalization below is a no-op; on a 2-core
    sandboxed container (shared cores with the sandbox supervisor,
    per-syscall sentry overhead) it is measurably below 1 for ANY
    workload, including two bare numpy loops. Shared by the cluster
    phase's routing-tier gate and the join phase's distributed
    stage-2 gate."""
    import subprocess

    worker = (
        "import os,sys,time\n"
        "import numpy as np\n"
        "pin=int(sys.argv[1])\n"
        "if pin>=0 and hasattr(os,'sched_setaffinity'):\n"
        "    try: os.sched_setaffinity(0,{pin%max(1,os.cpu_count())})\n"
        "    except OSError: pass\n"
        "rng=np.random.default_rng(0)\n"
        "a=rng.integers(0,4,1_200_000)\n"
        "b=rng.integers(1,500,1_200_000).astype(np.int32)\n"
        "for _ in range(3):\n"
        "    m=b<400; k=a[m]; v=b[m]\n"
        "    out=np.zeros(4); np.add.at(out,k,v)\n"
        "t0=time.perf_counter()\n"
        "for i in range(20):\n"
        "    m=b<400+(i%16); k=a[m]; v=b[m]\n"
        "    c=np.bincount(k,minlength=4)\n"
        "    out=np.zeros(4); np.add.at(out,k,v)\n"
        "print(20/(time.perf_counter()-t0))\n"
    )

    def run(pins):
        procs = [subprocess.Popen(
            [sys.executable, "-c", worker, str(p)],
            stdout=subprocess.PIPE, text=True) for p in pins]
        rates = []
        for p in procs:
            out, _ = p.communicate(timeout=120)
            rates.append(float(out.strip()))
        return rates

    solo = run([0])[0]
    duo = run([0, 1])
    if solo <= 0:
        return 1.0
    return max(0.1, min(1.0, sum(duo) / (2 * solo)))


def _bench_join_distributed():
    """detail.join.distributed: the server-side shuffle exchange
    sub-phase (ISSUE 16). Spawns 1- and 2-server OS-PROCESS clusters
    (``admin start-server --no-device``, pinned cores, FileRegistry —
    the cluster-phase recipe; real gRPC between servers is the whole
    point: partition ships cross process boundaries) holding a
    replicated fact-fact pair, and measures DISTRIBUTED stage-2 QPS at
    each width over an offered-load ladder.

    Gates (folded into the join phase's violations → exit 6):

    - zero query errors/partials at every width, rows bit-exact against
      the broker-local SHUFFLE reference (integer measures only — SUM
      over int64 merges exactly in any partition order);
    - stage-2 speedup at 2 servers (qps2/qps1), normalized by the box's
      own 2-process ceiling, >= 1.6x — one bounded retry of the pair,
      per-width peak kept (the cluster phase's noise policy);
    - a chaos run (``PINOT_TPU_FAULTS=exchange.transfer@srv_1=error#2``
      armed in every server process, exchange buffer squeezed to 64 KiB
      so every partition spills to the mmap warm tier): ZERO errors —
      the broker's exclude-and-retry must absorb the injected transfer
      faults in-band — with at least one retry observed, at least one
      spill counted, and rows still bit-exact.
    """
    import shutil
    import subprocess
    import threading as _threading

    from pinot_tpu.broker.broker import Broker
    from pinot_tpu.cluster.registry import FileRegistry, Role
    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.controller.controller import Controller
    from pinot_tpu.storage.creator import build_segment

    detail: dict = {}
    violations: list = []
    cores = os.cpu_count() or 2
    # fact-fact: both sides larger than any BROADCAST build budget, key
    # cardinality ~ build size so the join output stays ~ filtered-fact
    # sized (no row explosion polluting the stage-2 timing)
    n_fact, n_build, n_keys = 240_000, 120_000, 150_000
    rng = np.random.default_rng(61)
    fact = {
        "k": rng.integers(0, n_keys, n_fact).astype(np.int64),
        "v": rng.integers(1, 1000, n_fact).astype(np.int64),
    }
    fb = {
        "k2": rng.integers(0, n_keys, n_build).astype(np.int64),
        "mode": np.array([f"m{j}" for j in range(8)])[
            rng.integers(0, 8, n_build)],
        "w": rng.integers(1, 50, n_build).astype(np.int64),
    }
    fa_schema = Schema.build(
        name="fa_x", dimensions=[("k", DataType.LONG)],
        metrics=[("v", DataType.LONG)])
    fb_schema = Schema.build(
        name="fb_x",
        dimensions=[("k2", DataType.LONG), ("mode", DataType.STRING)],
        metrics=[("w", DataType.LONG)])

    seg_base = tempfile.mkdtemp(prefix="pinot_tpu_xjoin_segs_")
    for name, schema, data, n in (("fa_x", fa_schema, fact, n_fact),
                                  ("fb_x", fb_schema, fb, n_build)):
        for i, sl in enumerate([slice(0, n // 2), slice(n // 2, n)]):
            build_segment(schema, {k: v[sl] for k, v in data.items()},
                          os.path.join(seg_base, f"{name}_s{i}"),
                          TableConfig(table_name=name), f"{name}_s{i}")

    dist = "SET joinStrategy = 'distributed'; "
    fixed_sql = ("SELECT b.mode, COUNT(*), SUM(a.v), SUM(b.w) "
                 "FROM fa_x a JOIN fb_x b ON a.k = b.k2 "
                 "WHERE a.v < 500 GROUP BY b.mode ORDER BY b.mode")
    # literal sweep: distinct shapes per query, same template key
    sweep = [f"SELECT b.mode, COUNT(*), SUM(a.v) "
             f"FROM fa_x a JOIN fb_x b ON a.k = b.k2 "
             f"WHERE a.v < {400 + 25 * k} GROUP BY b.mode "
             f"ORDER BY b.mode" for k in range(16)]

    def run_xcluster(n_servers: int, extra_env=None, chaos: bool = False):
        """One isolated n-server cluster → entry dict (qps ladder, or
        the chaos/spill counters when ``chaos``)."""
        base = tempfile.mkdtemp(prefix=f"pinot_tpu_xjoin_{n_servers}_")
        reg_path = os.path.join(base, "cluster.json")
        procs = []
        broker = None
        try:
            registry = FileRegistry(reg_path)
            controller = Controller(registry, os.path.join(base, "ds"))
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["PYTHONPATH"] = os.pathsep.join(
                p for p in [os.path.dirname(os.path.abspath(__file__))]
                + env.get("PYTHONPATH", "").split(os.pathsep) if p)
            # same glibc-heap knobs as the cluster phase: page-table work
            # serializes ACROSS server processes under sandboxed kernels
            env.setdefault("MALLOC_MMAP_THRESHOLD_", "1073741824")
            env.setdefault("MALLOC_TRIM_THRESHOLD_", "1073741824")
            env.setdefault("MALLOC_TOP_PAD_", "268435456")
            env.update(extra_env or {})
            for i in range(n_servers):
                log_f = open(os.path.join(base, f"srv_{i}.log"), "w")
                p = subprocess.Popen(
                    [sys.executable, "-m", "pinot_tpu.tools.admin",
                     "start-server", "--registry", reg_path,
                     "--id", f"srv_{i}",
                     "--data-dir", os.path.join(base, f"s{i}"),
                     "--max-concurrent", str(max(1, cores // 2)),
                     "--no-device"],
                    stdout=log_f, stderr=subprocess.STDOUT, env=env)
                if hasattr(os, "sched_setaffinity"):
                    # one core per server: the 1-server baseline must not
                    # silently borrow the second core for its own scans
                    try:
                        os.sched_setaffinity(p.pid, {i % cores})
                    except OSError:
                        pass
                procs.append((p, log_f))
            t_end = time.time() + 60
            while time.time() < t_end:
                if len(registry.instances(
                        Role.SERVER, live_ttl_ms=10_000)) == n_servers:
                    break
                time.sleep(0.1)
            else:
                raise RuntimeError(
                    f"join phase: {n_servers} servers never registered")
            for name, schema in (("fa_x", fa_schema),
                                 ("fb_x", fb_schema)):
                controller.add_table(
                    TableConfig(table_name=name, replication=n_servers),
                    schema)
                for i in range(2):
                    controller.upload_segment(
                        name, os.path.join(seg_base, f"{name}_s{i}"))
            t_end = time.time() + 90
            while time.time() < t_end:
                evs = [registry.external_view(f"{t}_OFFLINE")
                       for t in ("fa_x", "fb_x")]
                if all(len(ev) == 2 and all(len(v) == n_servers
                                            for v in ev.values())
                       for ev in evs):
                    break
                time.sleep(0.1)
            else:
                raise RuntimeError("join phase: segments never loaded")

            broker = Broker(registry, timeout_s=30.0)
            ref = broker.execute(
                f"SET joinStrategy = 'shuffle'; {fixed_sql}")
            if ref.get("exceptions"):
                raise RuntimeError(
                    f"join phase shuffle ref failed: {ref['exceptions']}")
            ref_rows = ref["resultTable"]["rows"]
            warm = broker.execute(dist + fixed_sql)
            if warm.get("exceptions"):
                raise RuntimeError(f"join phase distributed warmup "
                                   f"failed: {warm['exceptions']}")
            if warm.get("joinStrategy") != "DISTRIBUTED":
                raise RuntimeError(
                    f"join phase: expected DISTRIBUTED, got "
                    f"{warm.get('joinStrategy')}")
            entry = {
                "errors": 0,
                "parity": warm["resultTable"]["rows"] == ref_rows,
                "partitions": warm.get("joinFanout"),
                "exchange_bytes": warm.get("exchangeBytes"),
                "partitions_shipped": warm.get("numPartitionsShipped"),
            }

            if chaos:
                # the warm query above already ran INTO the armed faults
                # (first distributed attempt dies typed, the retry
                # excludes srv_1) — fold its counters in
                retries = int(warm.get("numRetries") or 0)
                spills = int(warm.get("exchangeSpillCount") or 0)
                bad = 0
                parity = entry["parity"]
                for _ in range(10):
                    r = broker.execute(dist + fixed_sql)
                    if r.get("exceptions") or r.get("partialResult"):
                        bad += 1
                        continue
                    retries += int(r.get("numRetries") or 0)
                    spills += int(r.get("exchangeSpillCount") or 0)
                    if r["resultTable"]["rows"] != ref_rows:
                        parity = False
                entry.update({"errors": bad, "parity": parity,
                              "queries": 11, "retries_total": retries,
                              "spill_count": spills})
                return entry

            lock = _threading.Lock()
            errs = [0]

            def blast(width: int, nq: int) -> float:
                counter = [0]

                def worker():
                    while True:
                        with lock:
                            k = counter[0]
                            if k >= nq:
                                return
                            counter[0] += 1
                        r = broker.execute(dist + sweep[k % len(sweep)])
                        if r.get("exceptions") or r.get("partialResult"):
                            with lock:
                                errs[0] += 1

                t0 = time.perf_counter()
                ts = [_threading.Thread(target=worker)
                      for _ in range(width)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                return nq / (time.perf_counter() - t0)

            # offered-load ladder, peak kept: a closed loop sized to
            # saturate one server under-drives two (cluster-phase logic)
            rungs = {}
            qps = 0.0
            for width in sorted({n_servers, 2 * n_servers,
                                 4 * n_servers}):
                r = blast(width, max(16, min(48, 16 * width)))
                rungs[f"t{width}"] = round(r, 2)
                qps = max(qps, r)
            entry.update({"qps": round(qps, 2),
                          "qps_by_offered": rungs, "errors": errs[0]})
            return entry
        finally:
            if broker is not None:
                broker.close()
            for p, log_f in procs:
                p.terminate()
            for p, log_f in procs:
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
                log_f.close()
            shutil.rmtree(base, ignore_errors=True)

    try:
        entries: dict = {}

        def measure(n: int) -> None:
            e = run_xcluster(n)
            prev = entries.get(n)
            if prev is None or e["qps"] > prev["qps"]:
                entries[n] = e
            if e["errors"]:
                violations.append(
                    f"join.distributed: {e['errors']} query errors at "
                    f"{n} servers (bar: 0)")
            if not e["parity"]:
                violations.append(
                    f"join.distributed: rows != broker-local SHUFFLE "
                    f"reference at {n} servers")

        # ceiling sampled around the width runs, MEDIAN used — same
        # noise policy as the cluster phase's scaling gate
        ceilings = [process_scaling_ceiling()]
        measure(1)
        measure(2)
        ceilings.append(process_scaling_ceiling())

        def scaling() -> tuple:
            q1, q2 = entries[1]["qps"], entries[2]["qps"]
            speedup = q2 / q1 if q1 else 0.0
            ceiling = float(np.median(ceilings))
            return speedup, ceiling, \
                (speedup / ceiling if ceiling else 0.0)

        speedup, ceiling, norm = scaling()
        if norm < 1.6:
            # one bounded retry of the gated pair: shared-box noise only
            # ever under-measures a width's peak
            detail["retried"] = True
            measure(1)
            measure(2)
            ceilings.append(process_scaling_ceiling())
            speedup, ceiling, norm = scaling()
        if norm < 1.6:
            violations.append(
                f"join.distributed: stage-2 speedup at 2 servers "
                f"{norm:.2f}x normalized (raw {speedup:.2f}x / box "
                f"2-process ceiling {ceiling:.3f}) < 1.6x "
                f"(qps1={entries[1]['qps']}, qps2={entries[2]['qps']})")

        chaos = run_xcluster(
            2, chaos=True,
            extra_env={
                "PINOT_TPU_FAULTS": "exchange.transfer@srv_1=error#2",
                "PINOT_TPU_EXCHANGE_BUFFER_BYTES": str(64 << 10),
            })
        if chaos["errors"]:
            violations.append(
                f"join.distributed: {chaos['errors']} errors under "
                f"exchange.transfer chaos (bar: 0 — the broker's "
                f"exclude-and-retry must absorb injected faults)")
        if not chaos["retries_total"]:
            violations.append(
                "join.distributed: chaos faults never fired "
                "(numRetries stayed 0)")
        if not chaos["spill_count"]:
            violations.append(
                "join.distributed: 64 KiB exchange buffer never spilled")
        if not chaos["parity"]:
            violations.append(
                "join.distributed: chaos-run rows != reference")

        detail.update({
            "stage2_qps": {"n1": entries[1]["qps"],
                           "n2": entries[2]["qps"]},
            "qps_by_offered": {f"n{n}": entries[n]["qps_by_offered"]
                               for n in (1, 2)},
            "speedup_2": round(speedup, 3),
            "box_2proc_ceiling": round(ceiling, 3),
            "box_2proc_ceiling_samples": [round(c, 3) for c in ceilings],
            "speedup_2_normalized": round(norm, 3),
            "partitions": entries[2]["partitions"],
            "exchange_bytes": entries[2]["exchange_bytes"],
            "partitions_shipped": entries[2]["partitions_shipped"],
            "spill_count": chaos["spill_count"],
            "chaos": {"queries": chaos["queries"],
                      "errors": chaos["errors"],
                      "retries_total": chaos["retries_total"],
                      "spill_count": chaos["spill_count"],
                      "faults": "exchange.transfer@srv_1=error#2 + "
                                "64KiB exchange buffer"},
            "note": (
                f"peak DISTRIBUTED stage-2 QPS over an offered-load "
                f"ladder on a {n_fact}x{n_build}-row fact-fact join "
                f"sweep; each width is an isolated cluster of that many "
                f"server OS PROCESSES (pinned cores, real gRPC "
                f"partition ships), replication = width; speedup gate "
                f"normalized by the box's own 2-process ceiling; "
                f"cores={cores}"),
        })
    finally:
        shutil.rmtree(seg_base, ignore_errors=True)
    return detail, violations


def bench_join(n_fact: int = 300_000, iters: int = 5):
    """detail.join: the multi-stage engine phase (ISSUE 8). An SSB-style
    star — fact table joined against two dimension tables — versus the
    PRE-DENORMALIZED equivalent single table (the only shape the
    single-stage engine could express), with parity asserted between the
    two on every query and across BROADCAST / SHUFFLE strategies and
    device / host backends.

    Returns (detail, violations); violations non-empty fails the gate
    (standalone: ``python -m bench --phase join`` exits 6). Reports the
    star-join p50 per strategy (the strategy breakdown) next to the
    denormalized single-stage p50 the join replaces, then runs the
    DISTRIBUTED stage-2 sub-phase (``_bench_join_distributed``,
    ISSUE 16): server-fleet scaling gate + fault-injected chaos run."""
    import shutil
    import tempfile

    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.engine.engine import QueryEngine
    from pinot_tpu.storage.creator import build_segment

    rng = np.random.default_rng(31)
    n_parts, n_custs = 2000, 500
    part_cat = np.array([f"cat_{i % 25}" for i in range(n_parts)])
    cust_region = np.array([f"region_{i % 5}" for i in range(n_custs)])
    fact_part = rng.integers(0, n_parts, n_fact).astype(np.int64)
    fact_cust = rng.integers(0, n_custs, n_fact).astype(np.int64)
    fact = {
        "partkey": fact_part,
        "custkey": fact_cust,
        "revenue": rng.integers(1, 10_000, n_fact).astype(np.int64),
        "quantity": rng.integers(1, 50, n_fact).astype(np.int32),
    }
    denorm = {
        "category": part_cat[fact_part],
        "region": cust_region[fact_cust],
        "revenue": fact["revenue"],
        "quantity": fact["quantity"],
    }

    fact_schema = Schema.build(
        name="lineorder_j",
        dimensions=[("partkey", DataType.LONG), ("custkey", DataType.LONG)],
        metrics=[("revenue", DataType.LONG), ("quantity", DataType.INT)])
    part_schema = Schema.build(
        name="part_j",
        dimensions=[("pkey", DataType.LONG), ("category", DataType.STRING)],
        primary_key_columns=["pkey"])
    cust_schema = Schema.build(
        name="cust_j",
        dimensions=[("ckey", DataType.LONG), ("region", DataType.STRING)],
        primary_key_columns=["ckey"])
    denorm_schema = Schema.build(
        name="denorm_j",
        dimensions=[("category", DataType.STRING),
                    ("region", DataType.STRING)],
        metrics=[("revenue", DataType.LONG), ("quantity", DataType.INT)])

    base = tempfile.mkdtemp(prefix="bench_join_")
    detail: dict = {}
    violations: list = []
    try:
        engines = {}
        for name, dev in (("device", "auto"), ("host", None)):
            eng = QueryEngine() if dev else QueryEngine(device_executor=None)
            half = n_fact // 2
            for i, sl in enumerate([slice(0, half), slice(half, n_fact)]):
                eng.add_segment("lineorder_j", build_segment(
                    fact_schema, {k: v[sl] for k, v in fact.items()},
                    os.path.join(base, f"f{name}{i}"),
                    TableConfig(table_name="lineorder_j"), f"f{i}"))
                eng.add_segment("denorm_j", build_segment(
                    denorm_schema, {k: v[sl] for k, v in denorm.items()},
                    os.path.join(base, f"d{name}{i}"),
                    TableConfig(table_name="denorm_j"), f"d{i}"))
            eng.add_segment("part_j", build_segment(
                part_schema,
                {"pkey": np.arange(n_parts, dtype=np.int64),
                 "category": part_cat},
                os.path.join(base, f"p{name}"),
                TableConfig(table_name="part_j", is_dim_table=True), "p0"))
            eng.add_segment("cust_j", build_segment(
                cust_schema,
                {"ckey": np.arange(n_custs, dtype=np.int64),
                 "region": cust_region},
                os.path.join(base, f"c{name}"),
                TableConfig(table_name="cust_j", is_dim_table=True), "c0"))
            eng.table("part_j").is_dim_table = True
            eng.table("cust_j").is_dim_table = True
            engines[name] = eng

        star_1dim = (
            "SELECT p.category, SUM(o.revenue) FROM lineorder_j o "
            "JOIN part_j p ON o.partkey = p.pkey "
            "GROUP BY p.category ORDER BY p.category LIMIT 30")
        denorm_1dim = (
            "SELECT category, SUM(revenue) FROM denorm_j "
            "GROUP BY category ORDER BY category LIMIT 30")
        star_2dim = (
            "SELECT p.category, c.region, SUM(o.revenue), "
            "COUNT(*) FROM lineorder_j o "
            "JOIN part_j p ON o.partkey = p.pkey "
            "JOIN cust_j c ON o.custkey = c.ckey "
            "GROUP BY p.category, c.region "
            "ORDER BY p.category, c.region LIMIT 150")
        denorm_2dim = (
            "SELECT category, region, SUM(revenue), COUNT(*) "
            "FROM denorm_j GROUP BY category, region "
            "ORDER BY category, region LIMIT 150")

        def rows_of(resp):
            if resp.get("exceptions"):
                raise RuntimeError(f"join phase query failed: "
                                   f"{resp['exceptions'][0]}")
            return resp["resultTable"]["rows"]

        def p50_of(eng, sql):
            lat = []
            for _ in range(iters):
                t0 = time.perf_counter()
                rows_of(eng.execute(sql))
                lat.append((time.perf_counter() - t0) * 1e3)
            return float(np.percentile(lat, 50))

        dev = engines["device"]
        # parity: star join == pre-denormalized, every strategy + backend
        denorm_ref = {"1dim": rows_of(dev.execute(denorm_1dim)),
                      "2dim": rows_of(dev.execute(denorm_2dim))}
        for name, eng in engines.items():
            for strat in ("broadcast", "shuffle"):
                for tag, star_sql in (("1dim", star_1dim),
                                      ("2dim", star_2dim)):
                    got = rows_of(eng.execute(
                        f"SET joinStrategy='{strat}'; {star_sql}"))
                    if got != denorm_ref[tag]:
                        violations.append({
                            "check": f"star-vs-denorm parity "
                                     f"({name}/{strat}/{tag})",
                            "got": got[:3], "expected": denorm_ref[tag][:3],
                        })
        # device == host on a LEFT join (no denorm equivalent for misses)
        left_sql = (
            "SELECT p.category, COUNT(*) FROM lineorder_j o "
            "LEFT JOIN part_j p ON o.partkey = p.pkey "
            "GROUP BY p.category ORDER BY p.category LIMIT 30")
        if rows_of(dev.execute(left_sql)) != \
                rows_of(engines["host"].execute(left_sql)):
            violations.append({"check": "left-join device==host parity"})

        strategy_p50 = {}
        for strat in ("broadcast", "shuffle"):
            strategy_p50[strat.upper()] = {
                "star_1dim_p50_ms": round(p50_of(
                    dev, f"SET joinStrategy='{strat}'; {star_1dim}"), 2),
                "star_2dim_p50_ms": round(p50_of(
                    dev, f"SET joinStrategy='{strat}'; {star_2dim}"), 2),
            }
        join_p50 = min(s["star_2dim_p50_ms"] for s in strategy_p50.values())
        detail = {
            "n_fact_rows": n_fact,
            "n_dim_rows": {"part_j": n_parts, "cust_j": n_custs},
            "join_p50_ms": join_p50,
            "strategy_breakdown": strategy_p50,
            "denorm_p50_ms": {
                "1dim": round(p50_of(dev, denorm_1dim), 2),
                "2dim": round(p50_of(dev, denorm_2dim), 2),
            },
            "parity": "asserted (star==denorm, broadcast+shuffle, "
                      "device+host; left-join device==host)",
        }
        # distributed stage-2 sub-phase (ISSUE 16): OS-process server
        # fleet, normalized scaling gate + fault-injected chaos run
        dist_detail, dist_violations = _bench_join_distributed()
        detail["distributed"] = dist_detail
        # flat mirrors: the trend keys benchdiff tracks round-over-round
        detail["stage2_qps"] = dist_detail.get(
            "stage2_qps", {}).get("n2")
        detail["exchange_bytes"] = dist_detail.get("exchange_bytes")
        detail["spill_count"] = dist_detail.get("spill_count")
        violations.extend(dist_violations)
    finally:
        shutil.rmtree(base, ignore_errors=True)
    return detail, violations


# r05 had no concurrency detail (the sweep landed in r06): the embedded
# reference is the serialized-RTT figure its suite implies — one q2-shape
# query per ~115ms p50 ≈ 8.7 qps — the basis the ROADMAP's "5x the r05
# bench_concurrency figure at N=8" acceptance measures against. A
# recorded r05 concurrency.n8.qps value, when parseable, always wins.
_SUBRTT_QPS8_R05_REF = 8.7
# served-p50 gate floor: on a PCIe-local/CPU box link_floor is ~0, and
# 1.25x of ~nothing would gate pure host-side decode work; the absolute
# term covers compile-cache lookup + trim decode + result encode. On the
# tunneled bench box (link_floor ~90-100ms) the RTT term dominates.
_SUBRTT_ABS_FLOOR_MS = 25.0


def _load_r05_concurrency_qps8():
    """r05 concurrency qps at N=8 from BENCH_r05.json (wrapper/stdout
    tolerance lives in ONE place: tools/benchdiff.load_round), else the
    embedded reference."""
    path = os.environ.get(
        "PINOT_TPU_MICRO_REF",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_r05.json"))
    try:
        from pinot_tpu.tools.benchdiff import load_round

        conc = load_round(path).get("concurrency")
        qps = conc["n8"]["qps"] if isinstance(conc, dict) else None
        if isinstance(qps, (int, float)) and qps > 0:
            return float(qps), path
    except (OSError, ValueError, KeyError, TypeError, AttributeError):
        pass
    return _SUBRTT_QPS8_R05_REF, "embedded"


def bench_subrtt(n_rows: int = 1_000_000, iters: int = 11):
    """detail.subrtt: the sub-RTT serving phase (ISSUE 9). Gates

    - served-p50 for a repeat scalar aggregation (device partials cache
      warm) at or under ~1 RTT: ``served_p50_ms <=
      max(1.25 * link_floor_ms, 25ms)`` — one link round trip and host
      decode, no gather/kernel;
    - steady-state QPS at N=8 >= 5x the r05 concurrency reference;
    - device-reduce vs host-reduce parity across scalar, group-by
      (trimmed top-K), sealed + consuming(chunklet), solo + mesh (when
      >=2 devices), and cache-hit vs cache-miss paths — every violation
      fails the phase;
    - the trimmed group-by fetch must move FEWER bytes than the
      untrimmed form (the tentpole's whole point).

    Standalone: ``python -m bench --phase subrtt`` exits 7 on violation
    (faults=4 / observability=5 / join=6)."""
    import shutil
    import tempfile
    import threading

    import jax

    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import ChunkletConfig, TableConfig
    from pinot_tpu.engine.engine import QueryEngine
    from pinot_tpu.storage.creator import build_segment
    from pinot_tpu.storage.mutable import MutableSegment

    rng = np.random.default_rng(41)
    zones = np.array([f"zone_{i:03d}" for i in range(400)])
    z_ids = rng.integers(0, 400, n_rows)
    data = {
        "zone": zones[z_ids],
        "hour": rng.integers(0, 24, n_rows).astype(np.int32),
        "fare": rng.integers(1, 10_000, n_rows).astype(np.int64),
    }
    schema = Schema.build(
        name="subrtt",
        dimensions=[("zone", DataType.STRING)],
        metrics=[("hour", DataType.INT), ("fare", DataType.LONG)])
    cfg = TableConfig(table_name="subrtt")

    SQL_SCALAR = ("SELECT SUM(fare), COUNT(*) FROM subrtt "
                  "WHERE hour BETWEEN 2 AND 20")
    SQL_TOPK = ("SELECT zone, COUNT(*), SUM(fare) FROM subrtt "
                "GROUP BY zone ORDER BY SUM(fare) DESC, zone LIMIT 10")
    PARITY_SQLS = [
        SQL_SCALAR,
        SQL_TOPK,
        "SELECT zone, AVG(fare) FROM subrtt WHERE hour < 12 "
        "GROUP BY zone ORDER BY AVG(fare) LIMIT 7",
        "SELECT zone, COUNT(*) FROM subrtt GROUP BY zone LIMIT 12",
        "SELECT zone, MINMAXRANGE(fare) FROM subrtt "
        "GROUP BY zone ORDER BY MINMAXRANGE(fare) DESC, zone LIMIT 5",
    ]

    def _off(sql):
        return "SET useDeviceReduce=false; SET usePartialsCache=false; " + sql

    base = tempfile.mkdtemp(prefix="bench_subrtt_")
    detail: dict = {}
    violations: list = []
    try:
        eng = QueryEngine()
        host = QueryEngine(device_executor=None)
        n_segs = 4
        for i in range(n_segs):
            sl = slice(i * n_rows // n_segs, (i + 1) * n_rows // n_segs)
            seg = build_segment(
                schema, {k: v[sl] for k, v in data.items()},
                os.path.join(base, f"s{i}"), cfg, f"s{i}")
            eng.add_segment("subrtt", seg)
            host.add_segment("subrtt", seg)
        dev = eng.device

        link_floor_ms = round(measure_link_floor() * 1e3, 2)

        def rows_of(e, sql):
            r = e.execute(sql)
            if r.get("exceptions"):
                raise RuntimeError(f"subrtt query failed: {sql!r}: "
                                   f"{r['exceptions']}")
            return r["resultTable"]["rows"]

        # ---- parity matrix: device-reduce vs host-reduce, hit vs miss --
        for sql in PARITY_SQLS:
            want = rows_of(host, sql)
            got_on = rows_of(eng, sql)       # device reduce + cache (miss)
            got_hit = rows_of(eng, sql)      # cache HIT path
            got_off = rows_of(eng, _off(sql))  # untrimmed device form
            for name, got in (("device", got_on), ("cache_hit", got_hit),
                              ("reduce_off", got_off)):
                if got != want:
                    violations.append({
                        "gate": f"parity:{name}", "sql": sql,
                        "got": got[:3], "want": want[:3]})
        if dev.partials_hits < len(PARITY_SQLS):
            violations.append({"gate": "cache_hits",
                               "hits": dev.partials_hits,
                               "expected_at_least": len(PARITY_SQLS)})

        # mesh parity (>=2 devices only; the driver's multichip harness
        # covers the full mesh sweep)
        if jax.device_count() >= 2:
            from pinot_tpu.engine.device import DeviceExecutor
            from pinot_tpu.parallel.mesh import make_mesh
            from pinot_tpu.storage.segment import ImmutableSegment

            mesh_eng = QueryEngine(device_executor=DeviceExecutor(
                mesh=make_mesh(jax.device_count())))
            for i in range(n_segs):
                mesh_eng.add_segment(
                    "subrtt", ImmutableSegment(os.path.join(base, f"s{i}")))
            for sql in (SQL_TOPK, SQL_SCALAR):
                if rows_of(mesh_eng, sql) != rows_of(host, sql):
                    violations.append({"gate": "parity:mesh", "sql": sql})
            detail["mesh_devices"] = jax.device_count()
        else:
            detail["mesh_devices"] = 0

        # consuming (chunklet) parity: sealed-prefix device blocks + host
        # tail, trimmed vs host engine
        rt_cfg = TableConfig(
            table_name="subrtt_rt",
            chunklets=ChunkletConfig(enabled=True, rows_per_chunklet=65_536,
                                     device_min_rows=0))
        mseg = MutableSegment(schema, "subrtt_rt__0__0__0", rt_cfg)
        n_rt = 150_000
        rt_rows = [{"zone": str(data["zone"][i]),
                    "hour": int(data["hour"][i]),
                    "fare": int(data["fare"][i])} for i in range(n_rt)]
        for off in range(0, n_rt, 8192):
            mseg.index_batch(rt_rows[off:off + 8192])
        mseg.chunklet_index.promote()
        rt_eng = QueryEngine()
        rt_host = QueryEngine(device_executor=None)
        rt_eng.table("subrtt_rt").add_segment(mseg)
        rt_host.table("subrtt_rt").add_segment(mseg)
        rt_sql = ("SELECT zone, COUNT(*), SUM(fare) FROM subrtt_rt "
                  "GROUP BY zone ORDER BY SUM(fare) DESC, zone LIMIT 10")
        if rows_of(rt_eng, rt_sql) != rows_of(rt_host, rt_sql):
            violations.append({"gate": "parity:consuming", "sql": rt_sql})

        # ---- trimmed fetch bytes: the tentpole's byte shrink -----------
        b0 = dev.fetch_bytes_total
        rows_of(eng, "SET usePartialsCache=false; " + SQL_TOPK)
        trimmed_bytes = dev.fetch_bytes_total - b0
        b0 = dev.fetch_bytes_total
        rows_of(eng, _off(SQL_TOPK))
        untrimmed_bytes = dev.fetch_bytes_total - b0
        detail["fetch_bytes_trimmed"] = int(trimmed_bytes)
        detail["fetch_bytes_untrimmed"] = int(untrimmed_bytes)
        if trimmed_bytes >= untrimmed_bytes:
            violations.append({"gate": "trimmed_fetch_bytes",
                               "trimmed": int(trimmed_bytes),
                               "untrimmed": int(untrimmed_bytes)})

        # ---- served p50: repeat scalar agg, partials cache warm --------
        rows_of(eng, SQL_SCALAR)  # warm (cache insert)
        lat = run_samples(eng, SQL_SCALAR, iters)
        served_p50 = float(np.percentile(lat, 50)) * 1e3
        gate_ms = max(1.25 * link_floor_ms, _SUBRTT_ABS_FLOOR_MS)
        detail["served_p50_ms"] = round(served_p50, 2)
        detail["link_floor_ms"] = link_floor_ms
        detail["served_p50_gate_ms"] = round(gate_ms, 2)
        if served_p50 > gate_ms:
            violations.append({"gate": "served_p50",
                               "served_p50_ms": round(served_p50, 2),
                               "bound_ms": round(gate_ms, 2)})

        # ---- steady-state QPS at N=8 (cache-hot repeat stream) ---------
        def run_qps(n_threads, iters_per):
            barrier = threading.Barrier(n_threads + 1)
            errs = []

            def worker():
                try:
                    barrier.wait()
                    for _ in range(iters_per):
                        r = eng.execute(SQL_SCALAR)
                        if r.get("exceptions"):
                            errs.append(str(r["exceptions"])[:200])
                            return
                except Exception as e:  # noqa: BLE001
                    errs.append(repr(e))

            ts = [threading.Thread(target=worker) for _ in range(n_threads)]
            for t in ts:
                t.start()
            barrier.wait()
            t0 = time.perf_counter()
            for t in ts:
                t.join()
            wall = time.perf_counter() - t0
            if errs:
                raise RuntimeError(f"subrtt qps sweep failed: {errs[0]}")
            return n_threads * iters_per / wall

        run_qps(8, 2)  # warm the concurrent path
        qps8 = run_qps(8, 6)
        ref_qps, ref_src = _load_r05_concurrency_qps8()
        detail["qps8"] = round(qps8, 2)
        detail["qps8_reference"] = {"r05_qps8": ref_qps, "source": ref_src,
                                    "required_x": 5.0}
        if qps8 < 5.0 * ref_qps:
            violations.append({"gate": "qps8", "qps8": round(qps8, 2),
                               "required": round(5.0 * ref_qps, 2)})

        # ---- cache + reduce observability snapshot ---------------------
        hbm = dev.hbm_stats()
        detail["partials_cache"] = {
            k.replace("partials_cache_", ""): hbm[k]
            for k in ("partials_cache_entries", "partials_cache_bytes",
                      "partials_cache_hits", "partials_cache_misses",
                      "partials_cache_evictions",
                      "partials_cache_invalidations")}
        detail["device_reduce"] = {
            "queries": hbm["device_reduce_queries"],
            "ms_total": hbm["device_reduce_ms"]}
        detail["micro_device_trim_topk"] = _trim_topk_micro(G=1_000_000)
        detail["note"] = (
            "served_p50 is the cache-hot repeat scalar aggregation "
            "(device partials cache hit: one link RTT + host decode, no "
            "gather/kernel); gate = max(1.25*link_floor, 25ms abs floor "
            "for RTT-free boxes). qps8 = 8-thread cache-hot steady "
            "state vs 5x the r05 reference. fetch_bytes_* compare the "
            "top-K group-by's packed buffer with the on-device trim on "
            "vs off.")
        return detail, violations
    finally:
        shutil.rmtree(base, ignore_errors=True)


def bench_faults(n_queries: int = 40):
    """detail.faults: the failure-domain phase (ISSUE 6). A 3-server /
    replication-3 cluster over real gRPC serves a group-by while the
    fault harness blackholes one replica (800 ms connect-timeout shape)
    and delays another by 200 ms — hedging off vs on — plus a device
    quarantine demo (a poisoned template routes to host while another
    keeps running on device).

    Returns (detail, violations); violations non-empty fails the gate:
    the hedged run must report ZERO query errors and a p99 within 2x the
    healthy-cluster p99, and the quarantine breaker must isolate exactly
    the poisoned pipeline. Runnable standalone (CI gate without the full
    bench): ``python -m bench --phase faults``."""
    import shutil

    from pinot_tpu.broker.broker import Broker
    from pinot_tpu.cluster.registry import ClusterRegistry
    from pinot_tpu.common import faults
    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.controller.controller import Controller
    from pinot_tpu.server.server import ServerInstance
    from pinot_tpu.storage.creator import build_segment

    base = tempfile.mkdtemp(prefix="pinot_tpu_faults_")
    detail: dict = {}
    violations: list = []
    # 2 s budget: a blackholed primary without hedging costs at most the
    # budget (and surfaces as a flagged partial), never a broker-default
    # 10 s hang
    sql = ("SET timeoutMs = 2000; SELECT region, COUNT(*), SUM(amount) "
           "FROM sales GROUP BY region ORDER BY region")
    registry = ClusterRegistry()
    controller = Controller(registry, os.path.join(base, "ds"))
    servers = [
        ServerInstance(f"srv_{i}", registry, os.path.join(base, f"s{i}"),
                       device_executor=None)
        for i in range(3)
    ]
    for s in servers:
        s.start()
    try:
        schema = Schema.build(
            name="sales",
            dimensions=[("region", DataType.STRING)],
            metrics=[("amount", DataType.INT)],
        )
        cfg = TableConfig(table_name="sales", replication=3)
        controller.add_table(cfg, schema)
        rng = np.random.default_rng(5)
        rows_per, n_seg = 150_000, 4
        for i in range(n_seg):
            cols = {
                "region": np.array(["na", "eu", "apac", "latam"])[
                    rng.integers(0, 4, rows_per)],
                "amount": rng.integers(1, 500, rows_per).astype(np.int32),
            }
            d = os.path.join(base, f"up_s{i}")
            build_segment(schema, cols, d, cfg, f"sales_s{i}")
            controller.upload_segment("sales", d)
        t_end = time.time() + 30
        while time.time() < t_end:
            ev = registry.external_view("sales_OFFLINE")
            if len(ev) == n_seg and all(len(v) == 3 for v in ev.values()):
                break
            time.sleep(0.05)
        else:
            raise RuntimeError("faults phase: segments never fully loaded")

        def run_mode(broker, n):
            lats, errors = [], 0
            rows0 = None
            for _ in range(n):
                t0 = time.perf_counter()
                r = broker.execute(sql)
                lats.append((time.perf_counter() - t0) * 1e3)
                if r.get("exceptions"):
                    errors += 1
                else:
                    rows = r["resultTable"]["rows"]
                    if rows0 is None:
                        rows0 = rows
                    elif rows != rows0:
                        errors += 1  # parity violation counts as an error
            return {
                "p50_ms": round(float(np.percentile(lats, 50)), 2),
                "p99_ms": round(float(np.percentile(lats, 99)), 2),
                "errors": errors,
            }, rows0

        b = Broker(registry, timeout_s=10.0)
        healthy, rows_healthy = run_mode(b, n_queries)
        b.close()
        detail["healthy"] = healthy

        # one replica blackholed (800 ms connect-timeout shape: the RPC
        # hangs, then dies — long enough to dominate an unhedged tail,
        # short enough that abandoned attempts recycle pool threads and
        # teach the failure detector), one replica 200 ms slow
        def arm():
            faults.clear()
            faults.install(faults.Fault(
                point="transport.submit", target="srv_0",
                mode="blackhole", delay_ms=800))
            faults.install(faults.Fault(
                point="transport.submit", target="srv_1",
                mode="delay", delay_ms=200))

        arm()
        b = Broker(registry, timeout_s=10.0)
        hedging_off, rows_off = run_mode(b, n_queries)
        b.close()
        detail["faulted_hedging_off"] = hedging_off

        arm()
        b = Broker(registry, timeout_s=10.0)
        b.hedging_enabled = True
        b.hedge_delay_s = 0.025  # fixed trigger: the sweep is about tails
        hedging_on, rows_on = run_mode(b, n_queries)
        b.close()
        faults.clear()
        detail["faulted_hedging_on"] = hedging_on
        detail["note"] = (
            "p50/p99 over sequential group-by queries, 3 servers x "
            "replication 3, srv_0 blackholed (800ms) + srv_1 delayed "
            "200ms; hedging duplicates a slow request to a replica after "
            "25ms, first complete wins")

        if rows_on != rows_healthy:
            violations.append("hedged rows != healthy rows")
        if hedging_on["errors"]:
            violations.append(
                f"hedged run had {hedging_on['errors']} query errors "
                f"(bar: 0)")
        if hedging_on["p99_ms"] >= 2 * healthy["p99_ms"]:
            violations.append(
                f"hedged p99 {hedging_on['p99_ms']}ms >= 2x healthy p99 "
                f"{healthy['p99_ms']}ms")
    finally:
        faults.clear()
        for s in servers:
            try:
                s.stop(drain_timeout_s=0.2)
            except Exception:
                pass
        shutil.rmtree(base, ignore_errors=True)

    # ---- device quarantine demo: poisoned template → host, others stay
    # on device (in-process engine, same fault harness)
    from pinot_tpu.common import faults as _faults
    from pinot_tpu.engine.engine import QueryEngine
    from pinot_tpu.storage.segment import ImmutableSegment

    qbase = tempfile.mkdtemp(prefix="pinot_tpu_quarantine_")
    try:
        from pinot_tpu.common.datatypes import DataType
        from pinot_tpu.common.schema import Schema
        from pinot_tpu.common.table_config import TableConfig
        from pinot_tpu.storage.creator import build_segment

        schema = Schema.build(
            name="t", dimensions=[("tag", DataType.STRING)],
            metrics=[("m", DataType.INT), ("v", DataType.INT)])
        cfg = TableConfig(table_name="t")
        rng = np.random.default_rng(9)
        segs = []
        for i in range(2):
            cols = {
                "tag": np.array(["a", "b", "c"])[rng.integers(0, 3, 50_000)],
                "m": rng.integers(0, 1000, 50_000).astype(np.int32),
                "v": rng.integers(0, 1000, 50_000).astype(np.int32),
            }
            d = os.path.join(qbase, f"s{i}")
            build_segment(schema, cols, d, cfg, f"s{i}")
            segs.append(ImmutableSegment(d))
        eng = QueryEngine()
        host = QueryEngine(device_executor=None)
        for s in segs:
            eng.add_segment("t", s)
            host.add_segment("t", s)
        poisoned = "SELECT SUM(m) FROM t"
        healthy_sql = "SELECT SUM(v) FROM t WHERE tag <> 'zz'"
        _faults.install(_faults.Fault(
            point="device.launch", target="sum(m)", mode="error"))
        r_p = eng.execute(poisoned)
        stats = eng.device.hbm_stats()
        leaves_before = eng.device.fetch_leaves_total
        r_h = eng.execute(healthy_sql)
        healthy_on_device = eng.device.fetch_leaves_total > leaves_before
        _faults.clear()
        ok_parity = (
            r_p["resultTable"]["rows"]
            == host.execute(poisoned)["resultTable"]["rows"]
            and r_h["resultTable"]["rows"]
            == host.execute(healthy_sql)["resultTable"]["rows"])
        detail["device_quarantine"] = {
            "device_failures": stats["device_failures"],
            "quarantined_pipelines": stats["quarantined_pipelines"],
            "poisoned_answers_from_host": ok_parity,
            "other_template_on_device": bool(healthy_on_device),
        }
        if stats["quarantined_pipelines"] != 1:
            violations.append(
                f"expected exactly 1 quarantined pipeline, got "
                f"{stats['quarantined_pipelines']}")
        if not healthy_on_device:
            violations.append(
                "healthy template fell off the device alongside the "
                "poisoned one")
        if not ok_parity:
            violations.append("quarantine path broke result parity")
    finally:
        _faults.clear()
        shutil.rmtree(qbase, ignore_errors=True)
    return detail, violations


def bench_cluster(n_queries: int = 160, threads: int = 8):
    """detail.cluster: the cluster-serving phase (ISSUE 10). Spawns 1, 2
    (and 4, when the box has >= 6 cores — a 2-core container runs 2
    server processes, not 4) SERVER OS PROCESSES (``admin start-server
    --no-device``: host executors, real gRPC, FileRegistry coordination),
    builds a replica-group assignment (one group per server, each holding
    a full table copy) so every query routes to ONE group's instances
    with load-aware selection, and measures broker QPS at each width plus
    the broker result cache's hit latency and parity.

    Gates (standalone: ``python -m bench --phase cluster`` exits 8, after
    faults=4 / observability=5 / join=6 / subrtt=7):

    - zero query errors at every width;
    - scaling efficiency at 2 servers (qps2 / (2 * qps1)) >= 0.8;
    - result-cache hit p50 < 5 ms;
    - parity: cache-on hit rows == cache-on miss rows == cache-off rows
      == 1-server rows, bit-exact.

    Methodology: every server runs with the SAME admission config at
    every width (``--max-concurrent`` sized so width x admission fits the
    box's cores — over-admitting a 2-core container makes concurrent
    queries thrash instead of queue, and QPS *regresses* as offered load
    rises), and each width's QPS is the PEAK over an offered-load ladder
    rather than one fixed-concurrency point: a closed loop at the
    1-server saturation width would under-drive the 2-server cluster and
    misreport its capacity. The normalization ceiling is the MEDIAN of
    samples taken around the width runs, and a failed scaling gate earns
    one bounded retry of the 1-/2-server pair (per-width peak kept):
    shared-box noise only ever under-measures peak capacity, and a ratio
    of two numbers measured in different noise regimes flakes both ways.
    """
    import shutil
    import subprocess
    import threading as _threading

    from pinot_tpu.broker.broker import Broker
    from pinot_tpu.cluster.registry import FileRegistry, Role
    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.controller.controller import Controller
    from pinot_tpu.storage.creator import build_segment

    detail: dict = {"servers": {}}
    violations: list = []
    cores = os.cpu_count() or 2
    widths = [1, 2] + ([4] if cores >= 6 else [])
    # the blast broker reads the registry's routing-generation once per
    # query; on gVisor-class sandboxes that file read is a real syscall
    # round-trip, so give it the small TTL the knob exists for
    os.environ["PINOT_TPU_PINOT_BROKER_ROUTING_GEN_TTL_MS"] = "100"
    # heavy enough that SERVER scan CPU dominates the per-query budget:
    # the 1-server baseline must be bound by its (pinned) server core,
    # not by how much broker work fits on the spare core, or the ratio
    # measures the broker instead of the routing tier
    n_seg, rows_per = 8, 500_000

    # segments are built once and uploaded into each width's fresh cluster
    seg_base = tempfile.mkdtemp(prefix="pinot_tpu_cluster_segs_")
    schema = Schema.build(
        name="clu",
        dimensions=[("region", DataType.STRING), ("zone", DataType.STRING)],
        metrics=[("amount", DataType.INT)],
    )
    rng = np.random.default_rng(10)
    for i in range(n_seg):
        cols = {
            "region": np.array(["na", "eu", "apac", "latam"])[
                rng.integers(0, 4, rows_per)],
            "zone": np.array([f"z{j}" for j in range(32)])[
                rng.integers(0, 32, rows_per)],
            "amount": rng.integers(1, 500, rows_per).astype(np.int32),
        }
        build_segment(schema, cols,
                      os.path.join(seg_base, f"s{i}"),
                      TableConfig(table_name="clu"), f"clu_s{i}")

    fixed_sql = ("SELECT region, COUNT(*), SUM(amount) FROM clu "
                 "GROUP BY region ORDER BY region")
    # literal sweep for the QPS runs: distinct queries (no result-cache
    # shortcut even when enabled; the cache figure is measured separately)
    sweep = [f"SELECT region, COUNT(*), SUM(amount) FROM clu "
             f"WHERE amount < {400 + k} GROUP BY region ORDER BY region"
             for k in range(16)]

    def run_cluster(n_servers: int):
        """One isolated n-server cluster → (qps entry, fixed-query rows,
        cache detail or None). Servers are separate OS processes so the
        scaling measurement reflects real parallel hardware, not GIL
        sharing."""
        base = tempfile.mkdtemp(prefix=f"pinot_tpu_cluster_{n_servers}_")
        reg_path = os.path.join(base, "cluster.json")
        procs = []
        broker = None
        cache_broker = None
        try:
            registry = FileRegistry(reg_path)
            controller = Controller(registry, os.path.join(base, "ds"))
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["PYTHONPATH"] = os.pathsep.join(
                p for p in [os.path.dirname(os.path.abspath(__file__))]
                + env.get("PYTHONPATH", "").split(os.pathsep) if p)
            # keep numpy scratch on the glibc heap instead of per-query
            # mmap/munmap churn: page-table work serializes ACROSS server
            # processes under sandboxed kernels (gVisor-class), turning a
            # 0.95-efficiency 2-process scan into 0.63 — measured on this
            # container with the identical workload
            env.setdefault("MALLOC_MMAP_THRESHOLD_", "1073741824")
            env.setdefault("MALLOC_TRIM_THRESHOLD_", "1073741824")
            env.setdefault("MALLOC_TOP_PAD_", "268435456")
            # one admission slot per core the width leaves each server:
            # identical config at every width, like a real fleet
            admission = max(1, cores // max(widths))
            for i in range(n_servers):
                log_f = open(os.path.join(base, f"srv_{i}.log"), "w")
                p = subprocess.Popen(
                    [sys.executable, "-m", "pinot_tpu.tools.admin",
                     "start-server", "--registry", reg_path,
                     "--id", f"srv_{i}",
                     "--data-dir", os.path.join(base, f"s{i}"),
                     "--max-concurrent", str(admission),
                     "--no-device"],
                    stdout=log_f, stderr=subprocess.STDOUT, env=env)
                if hasattr(os, "sched_setaffinity"):
                    # one core per server: the scaling ladder measures the
                    # ROUTING TIER, so the 1-server baseline must not
                    # silently borrow the second core for its own scans
                    try:
                        os.sched_setaffinity(p.pid, {i % cores})
                    except OSError:
                        pass
                procs.append((p, log_f))
            t_end = time.time() + 60
            while time.time() < t_end:
                live = registry.instances(Role.SERVER, live_ttl_ms=10_000)
                if len(live) == n_servers:
                    break
                time.sleep(0.1)
            else:
                raise RuntimeError(
                    f"cluster phase: {n_servers} servers never registered")
            cfg = TableConfig(table_name="clu", replication=n_servers)
            controller.add_table(cfg, schema)
            for i in range(n_seg):
                controller.upload_segment("clu", os.path.join(seg_base,
                                                              f"s{i}"))
            controller.setup_replica_groups("clu")
            t_end = time.time() + 90
            while time.time() < t_end:
                ev = registry.external_view("clu_OFFLINE")
                if len(ev) == n_seg and \
                        all(len(v) == n_servers for v in ev.values()):
                    break
                time.sleep(0.1)
            else:
                raise RuntimeError(
                    "cluster phase: segments never fully loaded")

            broker = Broker(registry, timeout_s=30.0)
            warm = broker.execute(fixed_sql)
            if warm.get("exceptions"):
                raise RuntimeError(f"cluster warmup failed: "
                                   f"{warm['exceptions']}")
            rows_fixed = warm["resultTable"]["rows"]
            if warm.get("numReplicaGroupsQueried") != 1:
                raise RuntimeError(
                    f"cluster phase: expected replica-group routing, got "
                    f"numReplicaGroupsQueried="
                    f"{warm.get('numReplicaGroupsQueried')}")

            errors = [0]
            issued = _threading.Lock()

            def blast(width: int, nq: int) -> float:
                counter = [0]

                def worker():
                    while True:
                        with issued:
                            k = counter[0]
                            if k >= nq:
                                return
                            counter[0] += 1
                        r = broker.execute(sweep[k % len(sweep)])
                        if r.get("exceptions") or r.get("partialResult"):
                            with issued:
                                errors[0] += 1

                t0 = time.perf_counter()
                ts = [_threading.Thread(target=worker)
                      for _ in range(width)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                return nq / (time.perf_counter() - t0)

            # offered-load ladder: peak QPS per width, not one fixed
            # concurrency (a single closed loop sized to saturate one
            # server under-drives two, and over-driving thrashes)
            ladder = sorted({n_servers, 2 * n_servers,
                             min(threads, 4 * n_servers)})
            rungs = {}
            qps = 0.0
            for width in ladder:
                per_rung = max(32, min(n_queries, 24 * width))
                rungs[f"t{width}"] = round(blast(width, per_rung), 2)
                qps = max(qps, rungs[f"t{width}"])
            entry = {
                "qps": round(qps, 2),
                "qps_by_offered": rungs,
                "errors": errors[0],
                "load_score_last": warm.get("loadScore"),
            }

            cache = None
            if n_servers == max(widths):
                # result cache sweep on the widest cluster: one miss fills,
                # repeats serve without a scatter (same rows, bit-exact)
                cache_broker = Broker(registry, timeout_s=30.0,
                                      result_cache=True)
                miss = cache_broker.execute(fixed_sql)
                hit_lats = []
                rows_hit = None
                hits_flagged = 0
                for _ in range(40):
                    t1 = time.perf_counter()
                    r = cache_broker.execute(fixed_sql)
                    hit_lats.append((time.perf_counter() - t1) * 1e3)
                    rows_hit = r["resultTable"]["rows"]
                    hits_flagged += 1 if r.get("resultCacheHit") else 0
                off = broker.execute(fixed_sql)
                cache = {
                    "miss_ms": round(miss["timeUsedMs"], 3),
                    "hit_p50_ms": round(
                        float(np.percentile(hit_lats, 50)), 3),
                    "hit_p99_ms": round(
                        float(np.percentile(hit_lats, 99)), 3),
                    "hits_flagged": hits_flagged,
                    "parity_on_off": rows_hit == off["resultTable"]["rows"],
                    "rows_hit": rows_hit,
                    "rows_miss": miss["resultTable"]["rows"],
                }
            return entry, rows_fixed, cache
        finally:
            if broker is not None:
                broker.close()
            if cache_broker is not None:
                cache_broker.close()
            for p, log_f in procs:
                p.terminate()
            for p, log_f in procs:
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    p.kill()
                log_f.close()
            shutil.rmtree(base, ignore_errors=True)

    try:
        rows_by_width: dict = {}
        cache_detail = None

        def measure(n: int) -> None:
            """Run one width; keep the PEAK qps seen for it across
            attempts (noise on a shared box only ever under-measures
            capacity), the best cache sweep, and every width's rows for
            the cross-width parity check."""
            nonlocal cache_detail
            entry, rows_fixed, cache = run_cluster(n)
            prev = detail["servers"].get(f"n{n}")
            if prev is None or entry["qps"] > prev["qps"]:
                detail["servers"][f"n{n}"] = entry
            rows_by_width.setdefault(n, []).append(rows_fixed)
            if cache is not None and (
                    cache_detail is None
                    or cache["hit_p50_ms"] < cache_detail["hit_p50_ms"]):
                cache_detail = cache
            if entry["errors"]:
                violations.append(
                    f"{entry['errors']} query errors at {n} servers "
                    f"(bar: 0)")

        # the ceiling is sampled around the width runs (and again around
        # any retry) and the MEDIAN used: the box's background noise
        # drifts minute to minute, and dividing a qps ratio measured in
        # one regime by a ceiling measured in another manufactures gate
        # flakes in both directions
        ceilings = [process_scaling_ceiling()]
        for n in widths:
            measure(n)
        ceilings.append(process_scaling_ceiling())

        def scaling() -> tuple:
            qps1 = detail["servers"]["n1"]["qps"]
            qps2 = detail["servers"]["n2"]["qps"]
            eff = qps2 / (2 * qps1) if qps1 else 0.0
            # normalize against what 2 CPU-bound processes can do AT ALL
            # on this box (1.0 on a real multi-core host): the gate
            # measures the routing tier, not the container's core count
            ceiling = float(np.median(ceilings))
            return eff, ceiling, (eff / ceiling if ceiling else 0.0)

        eff, ceiling, eff_norm = scaling()
        if eff_norm < 0.8:
            # one bounded retry of the gated pair before failing: a
            # transient neighbor on a shared box under-measures one
            # width's peak and fails the ratio on noise
            detail["retried"] = True
            for n in (1, 2):
                measure(n)
            ceilings.append(process_scaling_ceiling())
            eff, ceiling, eff_norm = scaling()
        detail["scaling_efficiency_2"] = round(eff, 3)
        detail["box_2proc_ceiling"] = round(ceiling, 3)
        detail["box_2proc_ceiling_samples"] = [
            round(c, 3) for c in ceilings]
        detail["scaling_efficiency_2_normalized"] = round(eff_norm, 3)
        if len(widths) > 2:
            qps1 = detail["servers"]["n1"]["qps"]
            qps4 = detail["servers"]["n4"]["qps"]
            detail["scaling_efficiency_4"] = round(qps4 / (4 * qps1), 3) \
                if qps1 else 0.0
        detail["note"] = (
            f"peak broker QPS over an offered-load ladder (up to "
            f"{threads} threads) on a {n_seg}x{rows_per}-row group-by "
            f"sweep; each width is an isolated cluster of that many "
            f"server OS PROCESSES (host executor, real gRPC, "
            f"FileRegistry), replica groups = one full copy per server, "
            f"load-aware group pick per query, per-server admission "
            f"sized to cores/width; cores={cores} caps the width ladder")
        if eff_norm < 0.8:
            violations.append(
                f"scaling efficiency at 2 servers {eff_norm:.3f} "
                f"(raw {eff:.3f} / box 2-process ceiling {ceiling:.3f}) "
                f"< 0.8 "
                f"(qps1={detail['servers']['n1']['qps']}, "
                f"qps2={detail['servers']['n2']['qps']})")
        rows_ref = rows_by_width[1][0]
        if any(rows != rows_ref
               for runs in rows_by_width.values() for rows in runs):
            violations.append("fixed-query rows differ across widths")
        if cache_detail is None:
            violations.append("result-cache sweep never ran")
        else:
            rows_hit = cache_detail.pop("rows_hit")
            rows_miss = cache_detail.pop("rows_miss")
            detail["result_cache"] = cache_detail
            if cache_detail["hit_p50_ms"] >= 5.0:
                violations.append(
                    f"result-cache hit p50 "
                    f"{cache_detail['hit_p50_ms']}ms >= 5ms")
            if not cache_detail["hits_flagged"]:
                violations.append("repeat queries never hit the cache")
            if not (rows_hit == rows_miss == rows_ref
                    and cache_detail["parity_on_off"]):
                violations.append(
                    "result-cache parity violated (hit vs miss vs "
                    "cache-off vs single-server)")
    finally:
        os.environ.pop("PINOT_TPU_PINOT_BROKER_ROUTING_GEN_TTL_MS", None)
        shutil.rmtree(seg_base, ignore_errors=True)
    return detail, violations


def bench_tiering(n_segments: int = 16, rows: int = 120_000,
                  iters: int = 12):
    """detail.tiering: the tiered-lifecycle phase (ISSUE 12,
    server/tiering.py). One server + broker over real gRPC serve a table
    whose modeled (ColPlan-width) bytes are >=10x the device batch-cache
    budget (env-scaled: the budget is set to table/12 so the ratio holds
    on any box), under a zipf-skewed per-segment workload.

    Gates (standalone: ``python -m bench --phase tiering`` exits 9, after
    cluster=8):
      - capacity: table_plan_bytes >= 10x the effective cache budget AND
        device resident bytes stay within 1.5x budget after the workload
        (peak RSS delta reported; loose 512MB backstop);
      - lifecycle: the tick demotes the cold tail (hot set fits the
        budget), a forced cold demotion serves an honest partial
        (numSegmentsCold >= 1, partialResult) and CONVERGES to the full
        answer once the touch-triggered hydration lands;
      - parity: the full-table aggregate answers identically all-hot,
        mixed hot/warm, and after the cold round trip (integer aggs —
        exact);
      - placement: a forced temperature flip through the tier-aware
        replica-group rebalance moves ONLY the flipped segment (registry
        simulation, 4 instances x R=2).

    Reported: per-tier p50/p99 (hot = device batch, warm = lazy-mmap host
    scan, cold = first-touch partial + hydration latency), tier counts,
    TierManager counters."""
    import resource
    import shutil
    import tempfile

    from pinot_tpu.broker.broker import Broker
    from pinot_tpu.cluster.registry import (
        ClusterRegistry,
        InstanceInfo,
        Role,
        SegmentRecord,
    )
    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.controller.controller import Controller, SegmentAssigner
    from pinot_tpu.server.server import ServerInstance
    from pinot_tpu.server.tiering import Tier, segment_plan_bytes
    from pinot_tpu.storage.creator import build_segment
    from pinot_tpu.storage.segment import ImmutableSegment

    detail: dict = {}
    violations: list = []
    base = tempfile.mkdtemp(prefix="pinot_tpu_tiering_")
    rss0 = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    server = broker = None
    try:
        schema = Schema.build(
            name="tiered",
            dimensions=[("sk", DataType.INT), ("tag", DataType.STRING)],
            metrics=[("v", DataType.INT)],
        )
        cfg = TableConfig(table_name="tiered")
        rng = np.random.default_rng(17)
        registry = ClusterRegistry()
        controller = Controller(registry, os.path.join(base, "deep"))
        controller.add_table(cfg, schema)
        expected_total = 0
        seg_names = []
        plan_total = 0
        dirs = []
        t_build = time.time()
        for i in range(n_segments):
            cols = {
                # sk is CONSTANT per segment: the broker's value pruner
                # routes "WHERE sk = i" to exactly one segment, so the
                # workload's skew reaches per-segment heat
                "sk": np.full(rows, i, dtype=np.int32),
                "tag": np.array([f"t{j}" for j in range(64)])[
                    rng.integers(0, 64, rows)],
                "v": rng.integers(0, 10_000, rows).astype(np.int32),
            }
            expected_total += int(cols["v"].sum())
            d = os.path.join(base, f"up{i}")
            build_segment(schema, cols, d, cfg, f"tiered_s{i}")
            plan_total += segment_plan_bytes(ImmutableSegment(d))
            dirs.append(d)
            seg_names.append(f"tiered_s{i}")
        detail["build_s"] = round(time.time() - t_build, 1)
        # env-scaled capacity squeeze: the batch-cache budget is 1/12 of
        # the table's modeled bytes — the acceptance "table >= 10x
        # MAX_CACHED_BYTES" holds whatever the box
        budget = max(1, plan_total // 12)
        server = ServerInstance(
            "srv_tiering", registry, os.path.join(base, "srv"),
            tier_overrides={
                "pinot.server.tier.enabled": True,
                # ticks run explicitly below, not on the sync cadence
                "pinot.server.tier.interval.ms": 3_600_000,
                "pinot.server.tier.hot.bytes": budget,
                "pinot.server.tier.hot.min.rate": 0.05,
            })
        dev = getattr(server.engine, "device", None)
        if dev is not None:
            dev.MAX_CACHED_BYTES = budget
        server.start()
        for d in dirs:
            controller.upload_segment("tiered", d)
        broker = Broker(registry, timeout_s=30.0)
        t0 = time.time()
        while time.time() - t0 < 30:
            tdm = server.engine.tables.get("tiered_OFFLINE")
            if tdm is not None and len(tdm.segments) == n_segments:
                break
            time.sleep(0.05)
        else:
            raise RuntimeError("segments never loaded")
        detail["table_plan_bytes"] = plan_total
        detail["cache_budget_bytes"] = budget
        detail["table_over_budget"] = round(plan_total / budget, 1)
        if plan_total < 10 * budget:
            violations.append(
                f"table {plan_total}B < 10x budget {budget}B")

        def q_seg(i):
            return broker.execute(
                f"SELECT COUNT(*), SUM(v) FROM tiered WHERE sk = {i}")

        full_sql = "SELECT COUNT(*), SUM(v) FROM tiered"
        r_all_hot = broker.execute(full_sql)
        if r_all_hot.get("exceptions"):
            raise RuntimeError(f"baseline failed: {r_all_hot}")
        rows_all_hot = r_all_hot["resultTable"]["rows"]
        if rows_all_hot[0][1] != expected_total:
            violations.append("all-hot SUM != expected")

        # skewed workload: hammer a 3-segment hot set, touch the rest
        # once — then tick so the lifecycle ranks and demotes
        hot_set = [0, 1, 2]
        for _ in range(4):
            for i in hot_set:
                q_seg(i)
        for i in range(n_segments):
            q_seg(i)
        server.tiers.tick()
        snap = server.tiers.snapshot().get("tiered_OFFLINE", {})
        n_hot = sum(1 for t in snap.values() if t == Tier.HOT)
        n_warm = sum(1 for t in snap.values() if t == Tier.WARM)
        detail["tiers_after_tick"] = {"hot": n_hot, "warm": n_warm,
                                      "cold": len(snap) - n_hot - n_warm}
        if dev is not None and n_warm == 0:
            violations.append(
                "tick demoted nothing under a 12x-over-budget table")

        # per-tier latency: hot (device batch resident) vs warm (lazy
        # mmap host scan)
        def p50_p99(fn):
            lat = []
            for _ in range(iters):
                t = time.perf_counter()
                r = fn()
                lat.append(time.perf_counter() - t)
                if r.get("exceptions"):
                    raise RuntimeError(str(r["exceptions"]))
            return (round(float(np.percentile(lat, 50)) * 1e3, 2),
                    round(float(np.percentile(lat, 99)) * 1e3, 2))

        hot_seg = next((int(n.rsplit("s", 1)[1]) for n, t in snap.items()
                        if t == Tier.HOT), hot_set[0])
        warm_seg = next((int(n.rsplit("s", 1)[1]) for n, t in snap.items()
                         if t == Tier.WARM), n_segments - 1)
        hot_p50, hot_p99 = p50_p99(lambda: q_seg(hot_seg))
        warm_p50, warm_p99 = p50_p99(lambda: q_seg(warm_seg))
        r_mixed = broker.execute(full_sql)
        if r_mixed["resultTable"]["rows"] != rows_all_hot:
            violations.append("mixed hot/warm parity violated")

        # forced cold flip: demote, observe the honest partial, converge
        cold_i = n_segments - 2
        cold_name = f"tiered_s{cold_i}"
        if not server.tiers.demote_to_cold("tiered_OFFLINE", cold_name):
            violations.append("forced cold demotion refused")
        t_cold = time.perf_counter()
        r_cold = broker.execute(full_sql)
        cold_first_ms = round((time.perf_counter() - t_cold) * 1e3, 2)
        if not r_cold.get("numSegmentsCold"):
            violations.append("cold query reported numSegmentsCold == 0")
        if not r_cold.get("partialResult"):
            violations.append("cold partial not flagged partialResult")
        hydrated = server.tiers.wait_hydrated(
            "tiered_OFFLINE", cold_name, 60)
        hydrate_ms = round((time.perf_counter() - t_cold) * 1e3, 2)
        if not hydrated:
            violations.append("hydration never landed")
        r_back = broker.execute(full_sql)
        if r_back["resultTable"]["rows"] != rows_all_hot \
                or r_back.get("numSegmentsCold"):
            violations.append("post-hydration parity violated")
        detail["per_tier"] = {
            "hot": {"p50_ms": hot_p50, "p99_ms": hot_p99},
            "warm": {"p50_ms": warm_p50, "p99_ms": warm_p99},
            "cold": {"first_touch_ms": cold_first_ms,
                     "hydrate_ms": hydrate_ms},
        }
        detail["tier_manager"] = server.tiers.stats()
        detail["num_segments_cold_seen"] = int(
            r_cold.get("numSegmentsCold", 0))

        # bounded memory: device residency within 1.5x the budget; RSS
        # delta is reported (loose backstop — the table is env-scaled
        # small, so the real capacity claim is the residency bound)
        if dev is not None:
            resident = dev.resident_bytes()
            detail["device_resident_bytes"] = int(resident)
            if resident > budget * 1.5:
                violations.append(
                    f"device resident {resident}B > 1.5x budget {budget}B")
        rss_delta_mb = (resource.getrusage(
            resource.RUSAGE_SELF).ru_maxrss - rss0) / 1024.0
        detail["peak_rss_delta_mb"] = round(rss_delta_mb, 1)
        if rss_delta_mb > 512:
            violations.append(
                f"peak RSS grew {rss_delta_mb:.0f}MB > 512MB backstop")

        # tier-aware rebalance: a temperature flip moves ONLY the
        # flipped segment (registry simulation, 4 instances x R=2)
        sim = ClusterRegistry()
        for j in range(4):
            sim.register_instance(
                InstanceInfo(f"sim{j}", Role.SERVER, grpc_port=7000 + j))
        sim.add_table(TableConfig(table_name="sim", replication=2),
                      schema, key="sim_OFFLINE")
        for n in seg_names:
            sim.add_segment(
                SegmentRecord(name=n, table="sim_OFFLINE", n_docs=rows),
                [])
        assigner = SegmentAssigner(sim)
        before = assigner.rebalance_replica_groups("sim_OFFLINE", 2)
        flipped = seg_names[3]
        after = assigner.rebalance_tiered(
            "sim_OFFLINE", 2, {flipped: Tier.COLD})
        moved = sorted(n for n in before
                       if sorted(before[n]) != sorted(after.get(n, ())))
        detail["rebalance_moved"] = moved
        if moved != [flipped]:
            violations.append(
                f"temperature flip moved {moved}, expected [{flipped}]")
        if len(after[flipped]) != 1 or after[flipped][0] not in before[flipped]:
            violations.append("cold segment not trimmed to a current "
                              "single replica")
    finally:
        try:
            if broker is not None:
                broker.close()
            if server is not None:
                server.stop()
        finally:
            shutil.rmtree(base, ignore_errors=True)
    return detail, violations


def bench_overload(knee_window_s: float = 2.0, spike_window_s: float = 4.0):
    """detail.overload: the closed-loop overload-survival phase
    (ISSUE 14). An in-process 2-server / replication-2 cluster behind an
    admission-enabled broker runs three sub-phases:

    1. **Knee search** — an OPEN-MODEL arrival-rate ladder (queries fire
       on a wall-clock schedule, not a closed loop): rates double until
       p99 blows past 4x the base p50 or errors appear; the knee is the
       last sustainable rung.
    2. **Tenant spike at 2x the knee** — tenant A's arrival rate jumps
       10x (total offered load ~2x knee) while tenant B keeps its steady
       dashboard cadence. Gates: tenant-B p99 moves <25% vs the same
       harness without the spike, tenant B sees ZERO hard errors, and
       every shed/degraded response is TYPED (sheddingReason /
       servedStale + retryAfterSeconds — never silent).
    3. **Autoscaler cycle** — a fresh 2-server cluster under sustained
       closed-loop pressure must scale to 4 servers (controller
       autoscaler, replica groups growing via the minimal-movement
       repair) and drain back to 2 when the load stops, with zero
       errors on a background query trickle through both transitions.

    Standalone: ``python -m bench --phase overload`` exits 10 on gate
    violation (after tiering=9)."""
    import shutil
    import threading as _threading
    from concurrent import futures as _futures

    from pinot_tpu.broker.admission import TenantAdmissionController
    from pinot_tpu.broker.broker import Broker
    from pinot_tpu.cluster.registry import ClusterRegistry, Role
    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.controller.controller import Controller
    from pinot_tpu.server.server import ServerInstance
    from pinot_tpu.storage.creator import build_segment

    detail: dict = {}
    violations: list = []
    base = tempfile.mkdtemp(prefix="pinot_tpu_overload_")
    # fast heartbeats so piggybacked pressure reaches the controller
    # autoscaler within its tick cadence
    os.environ["PINOT_TPU_PINOT_SERVER_HEARTBEAT_INTERVAL_MS"] = "300"

    schema = Schema.build(
        name="mt", dimensions=[("region", DataType.STRING)],
        metrics=[("amount", DataType.INT)])
    cfg = TableConfig(table_name="mt", replication=2)
    rng = np.random.default_rng(14)
    seg_dirs = []
    for i in range(4):
        rows = 60_000
        cols = {
            "region": np.array(["na", "eu", "apac", "latam"])[
                rng.integers(0, 4, rows)],
            "amount": rng.integers(1, 500, rows).astype(np.int32),
        }
        d = os.path.join(base, f"seg{i}")
        build_segment(schema, cols, d, cfg, f"mt_s{i}")
        seg_dirs.append(d)

    def start_cluster(n_servers, admission=None, result_cache=False,
                      tag=""):
        registry = ClusterRegistry()
        controller = Controller(registry, os.path.join(base, f"ds{tag}"))
        servers = [
            ServerInstance(f"osrv_{tag}{i}", registry,
                           os.path.join(base, f"s{tag}{i}"),
                           device_executor=None,
                           scheduler_name="tokenbucket",
                           max_concurrent_queries=2)
            for i in range(n_servers)]
        for s in servers:
            s.start()
        controller.add_table(cfg, schema)
        for d in seg_dirs:
            controller.upload_segment("mt", d)
        controller.setup_replica_groups("mt")
        t_end = time.time() + 30
        while time.time() < t_end:
            ev = registry.external_view("mt_OFFLINE")
            if len(ev) == len(seg_dirs) and \
                    all(len(v) >= min(2, n_servers) for v in ev.values()):
                break
            time.sleep(0.05)
        else:
            raise RuntimeError("overload phase: segments never loaded")
        broker = Broker(registry, timeout_s=10.0, admission=admission,
                        result_cache=result_cache)
        return registry, controller, servers, broker

    def scan_sql(k: int) -> str:
        """One scan query; distinct ``k`` = distinct literal digest (a
        COLD query the result cache cannot queue-jump), stable
        selectivity either way."""
        return (f"SELECT region, COUNT(*), SUM(amount) FROM mt "
                f"WHERE amount < 480 AND amount != {100000 + k} "
                f"GROUP BY region ORDER BY region")

    # tenant B is a dashboard: a small REPEATING panel set (these are
    # exactly the queries the cache + queue-jumping protect)
    b_pool = [scan_sql(-(j + 1)) for j in range(4)]
    sweep = [scan_sql(k) for k in range(32)]

    def open_model(broker, arrivals, pool):
        """Fire (delay_s, sql, bucket) arrivals on the wall clock; each
        result appends (latency_ms, resp) to its bucket list."""
        t0 = time.perf_counter()
        futs = []
        for delay, sql, bucket in arrivals:
            now = time.perf_counter() - t0
            if delay > now:
                time.sleep(delay - now)

            def run(sql=sql, bucket=bucket):
                q0 = time.perf_counter()
                r = broker.execute(sql)
                bucket.append(((time.perf_counter() - q0) * 1e3, r))

            futs.append(pool.submit(run))
        for f in futs:
            f.result()

    def ladder_arrivals(rate, window_s, tenant, bucket, sql_fn):
        n = max(4, int(rate * window_s))
        return [(i / rate, f"SET workloadName='{tenant}'; {sql_fn(i)}",
                 bucket)
                for i in range(n)]

    def p(lats, q):
        return float(np.percentile(np.asarray(lats), q)) if lats else 0.0

    # ---- sub-phase 1: open-model knee search -----------------------------
    registry, controller, servers, broker = start_cluster(
        2, admission=None, tag="k")
    try:
        warm = broker.execute(sweep[0])
        if warm.get("exceptions"):
            raise RuntimeError(f"overload warmup failed: "
                               f"{warm['exceptions']}")
        pool = _futures.ThreadPoolExecutor(max_workers=32)
        rungs = {}
        knee = 0.0
        base_p50 = None
        rate = 16.0
        while rate <= 512.0:
            bucket: list = []
            open_model(broker, ladder_arrivals(
                rate, knee_window_s, "probe", bucket,
                lambda i: sweep[i % len(sweep)]), pool)
            lats = [entry[0] for entry in bucket]
            errs = sum(1 for _l, r in bucket if r.get("exceptions"))
            p50, p99 = p(lats, 50), p(lats, 99)
            if base_p50 is None:
                base_p50 = p50
            rungs[f"r{int(rate)}"] = {
                "offered_qps": rate, "p50_ms": round(p50, 2),
                "p99_ms": round(p99, 2), "errors": errs}
            if errs or p99 > 4 * max(base_p50, 1.0):
                break
            knee = rate
            rate *= 2
        pool.shutdown(wait=True)
        detail["ladder"] = rungs
        detail["knee_qps"] = knee
        if knee <= 0:
            violations.append("open-model ladder never found a "
                              "sustainable knee rung")
    finally:
        broker.close()
        for s in servers:
            s.stop(drain_timeout_s=0.5)

    # ---- sub-phase 2: 10x tenant-A spike at 2x the knee ------------------
    if knee > 0:
        a_base = max(2.0, knee / 5.0)
        a_spike = 10.0 * a_base          # total offered ~2x knee
        b_rate = min(10.0, max(4.0, knee / 8.0))
        adm = TenantAdmissionController(
            rate_qps=a_base, burst=2 * a_base,
            tenant_overrides={"tenantB": {"rate": 1000.0, "burst": 100.0}})
        registry, controller, servers, broker = start_cluster(
            2, admission=adm, result_cache=True, tag="m")
        try:
            # prewarm tenant B's dashboard pool: baseline and spike runs
            # then compare warm-cache against warm-cache, so the delta
            # measures the SPIKE's effect, not a first-touch cold scan
            for sql in b_pool:
                broker.execute(f"SET workloadName='tenantB'; {sql}")
            offset = [0]

            def run_mix(a_rate):
                pool = _futures.ThreadPoolExecutor(max_workers=48)
                a_bucket: list = []
                b_bucket: list = []
                base_k = offset[0]
                # tenant A's queries carry DISTINCT literals (cold scans
                # across both runs — the spike the caches can't absorb);
                # tenant B cycles its fixed panel pool
                arrivals = sorted(
                    ladder_arrivals(
                        a_rate, spike_window_s, "tenantA", a_bucket,
                        lambda i: scan_sql(base_k + i))
                    + ladder_arrivals(
                        b_rate, spike_window_s, "tenantB", b_bucket,
                        lambda i: b_pool[i % len(b_pool)]),
                    key=lambda e: e[0])
                open_model(broker, arrivals, pool)
                pool.shutdown(wait=True)
                offset[0] += int(a_rate * spike_window_s) + 16
                return a_bucket, b_bucket

            _a0, b0 = run_mix(a_base)          # baseline: A at normal rate
            a1, b1 = run_mix(a_spike)          # the 10x spike
            b0_lats = [entry[0] for entry in b0]
            b1_lats = [entry[0] for entry in b1]
            b0_p99, b1_p99 = p(b0_lats, 99), p(b1_lats, 99)
            delta_pct = ((b1_p99 - b0_p99) / b0_p99 * 100) if b0_p99 else 0.0
            b_hard_errors = sum(
                1 for _l, r in b1 if r.get("exceptions"))
            shed = sum(1 for _l, r in a1
                       if r.get("sheddingReason") and r.get("exceptions"))
            stale = sum(1 for _l, r in a1 if r.get("servedStale"))
            admitted_lats = [entry[0] for entry in (a1 + b1)
                             if not entry[1].get("exceptions")
                             and not entry[1].get("servedStale")]
            silent = 0
            for _l, r in a1 + b1:
                excs = r.get("exceptions") or []
                if excs and excs[0].get("errorCode") == 429 and (
                        not r.get("sheddingReason")
                        or r.get("retryAfterSeconds") is None):
                    silent += 1
                if r.get("servedStale") and (
                        r.get("staleAgeMs") is None
                        or not r.get("sheddingReason")):
                    silent += 1
            detail["p99_at_2x_knee_ms"] = round(p(admitted_lats, 99), 2)
            detail["tenant_b"] = {
                "baseline_p99_ms": round(b0_p99, 2),
                "spike_p99_ms": round(b1_p99, 2),
                "delta_pct": round(delta_pct, 1),
                "hard_errors": b_hard_errors,
            }
            detail["shed"] = {
                "rejected_429": shed, "served_stale": stale,
                "untyped_responses": silent,
                "spike_offered_qps": round(a_spike + b_rate, 1),
            }
            if b_hard_errors:
                violations.append(
                    f"tenant B saw {b_hard_errors} hard errors under the "
                    f"tenant-A spike (bar: 0)")
            if delta_pct >= 25.0:
                violations.append(
                    f"tenant-B p99 moved {delta_pct:.1f}% under the spike "
                    f"({b0_p99:.2f} -> {b1_p99:.2f} ms; bar: <25%)")
            if shed == 0:
                violations.append(
                    "the 10x spike was never shed (admission idle?)")
            if silent:
                violations.append(
                    f"{silent} shed/degraded responses lacked typed "
                    f"sheddingReason/servedStale fields")
        finally:
            broker.close()
            for s in servers:
                s.stop(drain_timeout_s=0.5)

    # ---- sub-phase 3: autoscaler 2 -> 4 -> 2 -----------------------------
    registry, controller, servers, broker = start_cluster(2, tag="a")
    scaled_servers: list = []
    counter = [2]
    try:
        def spawn():
            i = counter[0]
            counter[0] += 1
            s = ServerInstance(f"osrv_a{i}", registry,
                               os.path.join(base, f"sa{i}"),
                               device_executor=None,
                               scheduler_name="tokenbucket",
                               max_concurrent_queries=2)
            s.start()
            scaled_servers.append(s)
            return s.instance_id

        def drain(inst):
            for s in servers + scaled_servers:
                if s.instance_id == inst:
                    s.stop(drain_timeout_s=5.0)
                    return True
            return False

        controller.attach_autoscaler(
            spawn, drain, min_servers=2, max_servers=4,
            high_water=2.0, low_water=0.25, sustain_ticks=2,
            cooldown_ticks=1)
        assign_before = dict(registry.assignment("mt_OFFLINE"))

        trickle_errors = [0]
        trickle_n = [0]
        stop_trickle = _threading.Event()

        def trickle():
            i = 0
            while not stop_trickle.is_set():
                r = broker.execute(sweep[i % len(sweep)])
                trickle_n[0] += 1
                if r.get("exceptions"):
                    trickle_errors[0] += 1
                i += 1
                time.sleep(0.05)

        trickle_thread = _threading.Thread(target=trickle, daemon=True)
        trickle_thread.start()

        stop_load = _threading.Event()

        def loader():
            i = 0
            while not stop_load.is_set():
                broker.execute(sweep[i % len(sweep)])
                i += 1

        loaders = [_threading.Thread(target=loader, daemon=True)
                   for _ in range(8)]
        for t in loaders:
            t.start()
        live = lambda: len(registry.instances(  # noqa: E731
            Role.SERVER, live_ttl_ms=3000))
        t_end = time.time() + 60
        while time.time() < t_end and live() < 4:
            controller.run_autoscale()
            time.sleep(0.25)
        scaled_to = live()
        assign_mid = dict(registry.assignment("mt_OFFLINE"))
        stop_load.set()
        for t in loaders:
            t.join(3)
        t_end = time.time() + 90
        while time.time() < t_end and live() > 2:
            controller.run_autoscale()
            time.sleep(0.25)
        drained_to = live()
        stop_trickle.set()
        trickle_thread.join(5)
        moved_out = sorted(
            seg for seg in assign_mid
            if sorted(assign_mid.get(seg, ())) !=
            sorted(assign_before.get(seg, ())))
        # minimal movement: a segment moved at scale-out only when its
        # replica-group membership actually changed — i.e. it gained a
        # replica on a NEW server; none may merely shuffle between the
        # original two
        shuffled = [
            seg for seg in moved_out
            if not (set(assign_mid.get(seg, ()))
                    - set(assign_before.get(seg, ())))]
        detail["autoscaler"] = {
            "scaled_to": scaled_to, "drained_to": drained_to,
            "trickle_queries": trickle_n[0],
            "trickle_errors": trickle_errors[0],
            "segments_moved_at_scale_out": len(moved_out),
            "segments_shuffled_needlessly": len(shuffled),
            "actions": list(controller.autoscaler.actions),
            "state": registry.autoscaler_state(),
        }
        if scaled_to < 4:
            violations.append(
                f"autoscaler reached {scaled_to} servers under sustained "
                f"pressure (bar: 4)")
        if drained_to > 2:
            violations.append(
                f"autoscaler drained back to {drained_to} servers "
                f"(bar: 2)")
        if trickle_errors[0]:
            violations.append(
                f"{trickle_errors[0]} query errors during scale "
                f"transitions (bar: 0)")
        if shuffled:
            violations.append(
                f"{len(shuffled)} segments moved without a replica-group "
                f"membership change (rebalance not minimal)")
    finally:
        broker.close()
        for s in servers + scaled_servers:
            try:
                s.stop(drain_timeout_s=0.5)
            except Exception:  # noqa: BLE001 — already drained by scaler
                pass
        os.environ.pop("PINOT_TPU_PINOT_SERVER_HEARTBEAT_INTERVAL_MS",
                       None)
        shutil.rmtree(base, ignore_errors=True)
    return detail, violations


def bench_adaptive(train_n: int = 8, iters: int = 7):
    """detail.adaptive: the feedback-loop phase (ISSUE 17). Two defaults
    are deliberately mis-tuned and the plan advisor must rescue both
    from measurements alone:

    - **join**: a fact-sized (> BROADCAST_MAX_BUILD_ROWS) build table is
      mis-registered as a dimension table, so the static planner picks
      BROADCAST for a fact-fact shape. The advisor's measured build-side
      rows must converge the pick to SHUFFLE (stamped
      ``ADVISOR(joinStrategy=...)``).
    - **blockskip**: a range filter with interval structure the zone
      maps can act on but ZERO selectivity (every block matches), so the
      default engages the skip path and pays candidate-gather + in-kernel
      dense-fallback overhead for nothing. The advisor's measured
      ``blocks_scanned/blocks_total`` must converge the template to the
      dense form (stamped ``ADVISOR(blockSkip=dense)``).

    Gates (standalone: ``python -m bench --phase adaptive`` exits 11,
    after the full run's other gates):

    - each scenario converges (first stamped response) within
      ``train_n`` queries;
    - post-convergence advisor-on p50 lands within 10% of the hand-tuned
      p50 (``SET joinStrategy='shuffle'`` / ``SET useBlockSkip=false``
      with the advisor off) — a 0.5 ms absolute allowance absorbs timer
      jitter on fast queries;
    - ZERO parity drift: every advisor-on response row-set is bit-exact
      against its ``SET useAdvisor=false`` twin, throughout training and
      after convergence;
    - the learned decisions are visible in EXPLAIN ANALYZE.

    ``usePartialsCache=false`` rides every single-stage query so each
    execution is real (a cache hit would neither measure nor prove
    parity); queries-to-converge is reported as an info trend line for
    benchdiff, never gated (it moves with min-samples/reprobe tuning)."""
    import shutil
    import tempfile

    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import IndexingConfig, TableConfig
    from pinot_tpu.engine.engine import QueryEngine
    from pinot_tpu.query2.logical import BROADCAST_MAX_BUILD_ROWS
    from pinot_tpu.storage.creator import build_segment

    rng = np.random.default_rng(47)
    base = tempfile.mkdtemp(prefix="bench_adaptive_")
    detail: dict = {}
    violations: list = []

    def rows_of(resp):
        if resp.get("exceptions"):
            raise RuntimeError(f"adaptive phase query failed: "
                               f"{resp['exceptions'][0]}")
        return resp["resultTable"]["rows"]

    def p50_of(eng, sql, warm: int = 1):
        for _ in range(warm):
            rows_of(eng.execute(sql))
        lat = []
        for _ in range(iters):
            t0 = time.perf_counter()
            rows_of(eng.execute(sql))
            lat.append((time.perf_counter() - t0) * 1e3)
        return float(np.percentile(lat, 50))

    def train(eng, scenario, sql_of, stamp):
        """Run advisor-on queries (varying literals so every execution
        measures) until a response carries ``stamp``; each one is parity-
        checked bit-exact against its SET useAdvisor=false twin."""
        converge_at = None
        for i in range(1, train_n + 1):
            sql = sql_of(i)
            resp = eng.execute(sql)
            twin = eng.execute(f"SET useAdvisor = false; {sql}")
            if rows_of(resp) != rows_of(twin):
                violations.append({
                    "scenario": scenario, "check": "parity", "query": i,
                    "got": rows_of(resp)[:3], "expected": rows_of(twin)[:3]})
            if converge_at is None and any(
                    stamp in line
                    for line in resp.get("advisorDecisions") or ()):
                converge_at = i
        if converge_at is None:
            violations.append({
                "scenario": scenario,
                "check": f"convergence within {train_n} queries",
                "stamp": stamp})
        return converge_at

    def gate_p50(scenario, converged, hand):
        if converged > hand * 1.10 + 0.5:
            violations.append({
                "scenario": scenario,
                "check": "converged p50 within 10% of hand-tuned",
                "converged_p50_ms": round(converged, 2),
                "hand_tuned_p50_ms": round(hand, 2)})

    try:
        eng = QueryEngine()

        # ---- scenario 1: mis-tuned join strategy -------------------------
        # build side: 1 row past the broadcast cap, mis-flagged dim
        n_build = BROADCAST_MAX_BUILD_ROWS + 1
        n_fact = 120_000
        build_schema = Schema.build(
            name="adaptdim",
            dimensions=[("bkey", DataType.LONG), ("grp", DataType.LONG)],
            primary_key_columns=["bkey"])
        fact_schema = Schema.build(
            name="adaptfact",
            dimensions=[("k", DataType.LONG)],
            metrics=[("rev", DataType.LONG)])
        eng.add_segment("adaptdim", build_segment(
            build_schema,
            {"bkey": np.arange(n_build, dtype=np.int64),
             "grp": (np.arange(n_build, dtype=np.int64) % 40)},
            os.path.join(base, "dim"),
            TableConfig(table_name="adaptdim", is_dim_table=True), "d0"))
        eng.add_segment("adaptfact", build_segment(
            fact_schema,
            {"k": rng.integers(0, n_build, n_fact).astype(np.int64),
             "rev": rng.integers(1, 1000, n_fact).astype(np.int64)},
            os.path.join(base, "fact"),
            TableConfig(table_name="adaptfact"), "f0"))
        eng.table("adaptdim").is_dim_table = True

        join_sql = (
            "SELECT d.grp, SUM(o.rev) FROM adaptfact o "
            "JOIN adaptdim d ON o.k = d.bkey "
            "GROUP BY d.grp ORDER BY d.grp LIMIT 50")
        # literals don't vary (the multi-stage path re-executes fully);
        # the template key is literal-free either way
        join_converge = train(eng, "join", lambda i: join_sql,
                              "ADVISOR(joinStrategy=SHUFFLE")
        join_hand = p50_of(eng, "SET useAdvisor = false; "
                                "SET joinStrategy = 'shuffle'; " + join_sql)
        join_mistuned = p50_of(eng, "SET useAdvisor = false; " + join_sql)
        join_converged = p50_of(eng, join_sql)
        gate_p50("join", join_converged, join_hand)
        ea = eng.execute("EXPLAIN ANALYZE " + join_sql)
        join_ea_ok = "ADVISOR(" in json.dumps(ea)
        if not join_ea_ok:
            violations.append({"scenario": "join",
                               "check": "ADVISOR line in EXPLAIN ANALYZE"})
        detail["join"] = {
            "n_build_rows": n_build,
            "queries_to_converge": join_converge,
            "mistuned_p50_ms": round(join_mistuned, 2),
            "hand_tuned_p50_ms": round(join_hand, 2),
            "converged_p50_ms": round(join_converged, 2),
            "explain_analyze_stamped": join_ea_ok,
            "note": ("mis-registered dim table past the broadcast cap: "
                     "the runner's over-cap guard bounds the blast radius "
                     "at run time; the advisor makes the SHUFFLE pick "
                     "explicit, stamped, and available to the broker's "
                     "distributed probe (measured rows beat estimates)"),
        }

        # ---- scenario 2: mis-tuned block skip ----------------------------
        # time-ordered zone-mapped table; the training filter matches
        # EVERY block (selectivity 1.0) so the skip default buys nothing
        n_seg, seg_rows = 2, 524_288
        bs_schema = Schema.build(
            name="adaptbs",
            dimensions=[("ts", DataType.LONG)],
            metrics=[("val", DataType.INT)])
        bs_cfg = TableConfig(
            table_name="adaptbs",
            indexing=IndexingConfig(no_dictionary_columns=["ts"]))
        for i in range(n_seg):
            n = seg_rows
            eng.add_segment("adaptbs", build_segment(
                bs_schema,
                {"ts": np.int64(i) * n + np.arange(n, dtype=np.int64),
                 "val": rng.integers(0, 10_000, n).astype(np.int32)},
                os.path.join(base, f"bs{i}"), bs_cfg, f"bs{i}"))
        total = n_seg * seg_rows

        def bs_select(i):
            # literal varies (dodges nothing here — the partials cache is
            # off — but keeps the training honest about literal-free
            # template keying); every bound covers the full ts domain
            return (f"SELECT COUNT(*), SUM(val) FROM adaptbs "
                    f"WHERE ts BETWEEN 0 AND {total * 10 + i}")

        def bs_sql(i):
            return "SET usePartialsCache = false; " + bs_select(i)

        bs_converge = train(eng, "blockskip", bs_sql,
                            "ADVISOR(blockSkip=dense")
        bs_hand = p50_of(eng, "SET useAdvisor = false; "
                              "SET useBlockSkip = false; " + bs_sql(0))
        bs_mistuned = p50_of(eng, "SET useAdvisor = false; " + bs_sql(0))
        bs_converged = p50_of(eng, bs_sql(0))
        gate_p50("blockskip", bs_converged, bs_hand)
        ea = eng.execute("SET usePartialsCache = false; "
                         "EXPLAIN ANALYZE " + bs_select(0))
        bs_ea_ok = "ADVISOR(" in json.dumps(ea)
        if not bs_ea_ok:
            violations.append({"scenario": "blockskip",
                               "check": "ADVISOR line in EXPLAIN ANALYZE"})
        detail["blockskip"] = {
            "n_rows": total,
            "queries_to_converge": bs_converge,
            "mistuned_p50_ms": round(bs_mistuned, 2),
            "hand_tuned_p50_ms": round(bs_hand, 2),
            "converged_p50_ms": round(bs_converged, 2),
            "explain_analyze_stamped": bs_ea_ok,
        }
        detail["parity"] = ("asserted bit-exact vs SET useAdvisor=false "
                            "on every training query, both scenarios")
    finally:
        shutil.rmtree(base, ignore_errors=True)
    return detail, violations


def _vm_rss_mb() -> float:
    """This process's resident set in MB (/proc/self/status VmRSS);
    0.0 where /proc is unavailable."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


def bench_frontdoor(n_queries: int = 240):
    """detail.frontdoor: the broker-fleet front door phase (ISSUE 18).
    Three sub-measurements:

    A. **Broker-tier scaling**: one server OS process serves a small
       table; 1 then 2 BROKER OS processes (``admin start-broker``,
       result cache ON via env config, fleet-registered in the shared
       FileRegistry) answer a cache-hot fixed query over HTTP. The
       client discovers both brokers from the registry (fleet.py —
       the bench never hardcodes the second URL) and rotates across
       them via ``broker_urls``. Gate: ``qps2/qps1`` normalized by the
       box's own 2-process ceiling >= 1.6 (a real 2-core-or-better host
       must nearly double; a 1-core sandbox is graded against what two
       pinned processes can do AT ALL there), zero errors, and the two
       brokers' cache hits answer bit-identically.

    B. **Streaming delivery**: an in-process 1-server cluster holds a
       10M-row table; ``Broker.execute_stream`` cursors the full SELECT
       through the chunked path while the bench samples VmRSS per chunk.
       Gates: peak RSS delta during the stream < 256 MB, and a running
       hash of the streamed rows equals the hash of the same query run
       BUFFERED (bit-identical rows, same order).

    C. **Fleet-fair admission**: two in-process brokers share one
       logical per-tenant budget via heartbeat-gossiped spend
       (fleet.py + admission.observe_peer_spend). Tenant A sprays BOTH
       brokers; gates: A's fleet-wide admitted count stays within one
       heartbeat of refill over the single-broker budget (not 2x), and
       tenant B's p99 drifts < 25% vs its solo baseline.

    Standalone: ``python -m bench --phase frontdoor`` exits 12 on gate
    failure (after adaptive=11). The scaling pair and the fairness
    drift each get one bounded retry: both divide two measurements
    taken in different noise regimes on a shared box.
    """
    import gc
    import hashlib
    import shutil
    import subprocess
    import threading as _threading
    import urllib.request

    from pinot_tpu.broker.broker import Broker
    from pinot_tpu.broker.admission import TenantAdmissionController
    from pinot_tpu.broker.fleet import BrokerFleetMember, discover_broker_urls
    from pinot_tpu.cluster.registry import ClusterRegistry, FileRegistry, Role
    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.controller.controller import Controller
    from pinot_tpu.server.server import ServerInstance
    from pinot_tpu.storage.creator import build_segment
    from pinot_tpu import client as pt_client

    detail: dict = {}
    violations: list = []
    cores = os.cpu_count() or 2

    def _post(url: str, sql: str) -> dict:
        req = urllib.request.Request(
            url.rstrip("/") + "/query/sql",
            data=json.dumps({"sql": sql}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read().decode())

    # ---- A. broker-tier scaling over OS-process brokers ------------------
    def broker_scaling() -> dict:
        part: dict = {"brokers": {}}
        base = tempfile.mkdtemp(prefix="pinot_tpu_frontdoor_")
        reg_path = os.path.join(base, "cluster.json")
        procs = []
        broker_procs = []
        try:
            registry = FileRegistry(reg_path)
            controller = Controller(registry, os.path.join(base, "ds"))
            schema = Schema.build(
                name="fd",
                dimensions=[("region", DataType.STRING)],
                metrics=[("amount", DataType.INT)],
            )
            rng = np.random.default_rng(18)
            rows_per = 120_000
            for i in range(2):
                cols = {
                    "region": np.array(["na", "eu", "apac", "latam"])[
                        rng.integers(0, 4, rows_per)],
                    "amount": rng.integers(1, 500, rows_per).astype(np.int32),
                }
                build_segment(schema, cols, os.path.join(base, f"seg{i}"),
                              TableConfig(table_name="fd"), f"fd_s{i}")
            env = dict(os.environ)
            env["JAX_PLATFORMS"] = "cpu"
            env["PYTHONPATH"] = os.pathsep.join(
                p for p in [os.path.dirname(os.path.abspath(__file__))]
                + env.get("PYTHONPATH", "").split(os.pathsep) if p)
            # the whole point of this width ladder is the CACHE-HOT
            # broker tier: every broker process serves the same fixed
            # query from its own result cache after one warming miss
            env["PINOT_TPU_PINOT_BROKER_RESULTCACHE_ENABLED"] = "true"
            log_f = open(os.path.join(base, "srv.log"), "w")
            p = subprocess.Popen(
                [sys.executable, "-m", "pinot_tpu.tools.admin",
                 "start-server", "--registry", reg_path, "--id", "fd_srv",
                 "--data-dir", os.path.join(base, "sd"),
                 "--max-concurrent", "2", "--no-device"],
                stdout=log_f, stderr=subprocess.STDOUT, env=env)
            procs.append((p, log_f))
            t_end = time.time() + 60
            while time.time() < t_end:
                if len(registry.instances(Role.SERVER,
                                          live_ttl_ms=10_000)) == 1:
                    break
                time.sleep(0.1)
            else:
                raise RuntimeError("frontdoor: server never registered")
            controller.add_table(TableConfig(table_name="fd"), schema)
            for i in range(2):
                controller.upload_segment("fd", os.path.join(base, f"seg{i}"))
            t_end = time.time() + 60
            while time.time() < t_end:
                if len(registry.external_view("fd_OFFLINE")) == 2:
                    break
                time.sleep(0.1)
            else:
                raise RuntimeError("frontdoor: segments never assigned")

            def spawn_broker(i: int):
                blog = open(os.path.join(base, f"bk_{i}.log"), "w")
                bp = subprocess.Popen(
                    [sys.executable, "-m", "pinot_tpu.tools.admin",
                     "start-broker", "--registry", reg_path,
                     "--id", f"fd_bk_{i}", "--port", "0",
                     "--timeout-s", "30"],
                    stdout=blog, stderr=subprocess.STDOUT, env=env)
                if hasattr(os, "sched_setaffinity"):
                    try:
                        os.sched_setaffinity(bp.pid, {i % cores})
                    except OSError:
                        pass
                broker_procs.append((bp, blog))

            def wait_urls(n: int) -> list:
                # registry-driven discovery IS the surface under test:
                # the bench learns the brokers' ephemeral ports the same
                # way a client would, from their fleet registrations
                t_end = time.time() + 60
                while time.time() < t_end:
                    urls = discover_broker_urls(registry)
                    if len(urls) >= n:
                        return sorted(urls)
                    time.sleep(0.1)
                raise RuntimeError(
                    f"frontdoor: {n} brokers never became discoverable")

            fixed_sql = ("SELECT region, COUNT(*), SUM(amount) FROM fd "
                         "GROUP BY region ORDER BY region")

            def warm(url: str) -> dict:
                # first request pays the scatter and fills that broker's
                # cache; repeats must flag resultCacheHit
                r = _post(url, fixed_sql)
                if r.get("exceptions"):
                    raise RuntimeError(f"frontdoor warmup failed: "
                                       f"{r['exceptions']}")
                t_end = time.time() + 30
                while time.time() < t_end:
                    r = _post(url, fixed_sql)
                    if r.get("resultCacheHit"):
                        return r
                    time.sleep(0.05)
                raise RuntimeError(f"frontdoor: {url} never served a "
                                   f"cache hit")

            errors = [0]
            lock = _threading.Lock()

            def blast(urls: list, width: int, nq: int) -> float:
                counter = [0]

                def worker():
                    conn = pt_client.connect(broker_urls=list(urls),
                                             timeout_s=30.0)
                    try:
                        cur = conn.cursor()
                        while True:
                            with lock:
                                if counter[0] >= nq:
                                    return
                                counter[0] += 1
                            try:
                                cur.execute(fixed_sql)
                                cur.fetchall()
                            except pt_client.Error:
                                with lock:
                                    errors[0] += 1
                    finally:
                        conn.close()

                t0 = time.perf_counter()
                ts = [_threading.Thread(target=worker)
                      for _ in range(width)]
                for t in ts:
                    t.start()
                for t in ts:
                    t.join()
                return nq / (time.perf_counter() - t0)

            def measure(urls: list, label: str) -> None:
                rungs = {}
                qps = 0.0
                for width in (2, 4):
                    per_rung = max(60, n_queries // 2)
                    rungs[f"t{width}"] = round(
                        blast(urls, width, per_rung), 2)
                    qps = max(qps, rungs[f"t{width}"])
                prev = part["brokers"].get(label)
                entry = {"qps": round(qps, 2), "qps_by_offered": rungs,
                         "urls": len(urls)}
                if prev is None or entry["qps"] > prev["qps"]:
                    part["brokers"][label] = entry

            ceilings = [process_scaling_ceiling()]
            spawn_broker(0)
            urls1 = wait_urls(1)
            hit1 = warm(urls1[0])
            measure(urls1, "n1")
            spawn_broker(1)
            urls2 = wait_urls(2)
            url_b = next(u for u in urls2 if u not in urls1)
            hit2 = warm(url_b)
            measure(urls2, "n2")
            ceilings.append(process_scaling_ceiling())

            # cross-broker cache parity: two independent caches, same
            # table epochs, must answer the same bytes
            part["cache_parity"] = (
                hit1["resultTable"]["rows"] == hit2["resultTable"]["rows"])
            if not part["cache_parity"]:
                violations.append(
                    "frontdoor: cache-hit rows differ across brokers")

            def ratio() -> tuple:
                qps1 = part["brokers"]["n1"]["qps"]
                qps2 = part["brokers"]["n2"]["qps"]
                raw = qps2 / qps1 if qps1 else 0.0
                ceiling = float(np.median(ceilings))
                return raw, ceiling, (raw / ceiling if ceiling else 0.0)

            raw, ceiling, norm = ratio()
            if norm < 1.6:
                # one bounded retry of the gated pair: peak-per-width is
                # kept, and the ceiling is resampled in the same regime
                part["retried"] = True
                measure(urls1, "n1")
                measure(urls2, "n2")
                ceilings.append(process_scaling_ceiling())
                raw, ceiling, norm = ratio()
            part["qps2_over_qps1_raw"] = round(raw, 3)
            part["box_2proc_ceiling"] = round(ceiling, 3)
            part["box_2proc_ceiling_samples"] = [
                round(c, 3) for c in ceilings]
            part["qps2_over_qps1"] = round(norm, 3)
            part["errors"] = errors[0]
            if errors[0]:
                violations.append(
                    f"frontdoor: {errors[0]} client errors during "
                    f"rotation blasts (bar: 0)")
            if norm < 1.6:
                violations.append(
                    f"frontdoor: 2-broker QPS gain {norm:.3f} "
                    f"(raw {raw:.3f} / box 2-process ceiling "
                    f"{ceiling:.3f}) < 1.6 "
                    f"(qps1={part['brokers']['n1']['qps']}, "
                    f"qps2={part['brokers']['n2']['qps']})")
            return part
        finally:
            for bp, blog in broker_procs:
                bp.terminate()
            for p, log_f in procs:
                p.terminate()
            for bp, blog in broker_procs + procs:
                try:
                    bp.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    bp.kill()
                blog.close()
            shutil.rmtree(base, ignore_errors=True)

    # ---- B. streaming delivery: bounded RSS + bit-identity ---------------
    def streaming() -> dict:
        part: dict = {}
        base = tempfile.mkdtemp(prefix="pinot_tpu_frontdoor_strm_")
        n_seg, rows_per = 40, 250_000
        server = None
        broker = None
        try:
            registry = ClusterRegistry()
            controller = Controller(registry, os.path.join(base, "ds"))
            schema = Schema.build(
                name="strm",
                dimensions=[],
                metrics=[("a", DataType.INT), ("b", DataType.INT)],
            )
            cfg = TableConfig(table_name="strm")
            rng = np.random.default_rng(19)
            for i in range(n_seg):
                # values < 256 so the row tuples hold interned small
                # ints: the bench measures the STREAM's buffering, not
                # the cost of 20M distinct PyLong objects
                cols = {
                    "a": rng.integers(0, 256, rows_per).astype(np.int32),
                    "b": rng.integers(0, 256, rows_per).astype(np.int32),
                }
                build_segment(schema, cols, os.path.join(base, f"s{i}"),
                              cfg, f"strm_s{i}")
            server = ServerInstance("fd_strm_srv", registry,
                                    os.path.join(base, "sd"),
                                    device_executor=None)
            server.start()
            controller.add_table(cfg, schema)
            for i in range(n_seg):
                controller.upload_segment("strm", os.path.join(base,
                                                               f"s{i}"))
            t_end = time.time() + 120
            while time.time() < t_end:
                tdm = server.engine.tables.get("strm_OFFLINE")
                if tdm is not None and len(tdm.segments) == n_seg:
                    break
                time.sleep(0.1)
            else:
                raise RuntimeError("frontdoor: stream segments never "
                                   "loaded")
            broker = Broker(registry, broker_id="fd_strm_bk",
                            timeout_s=600.0)
            warm = broker.execute("SELECT COUNT(*) FROM strm")
            total_rows = warm["resultTable"]["rows"][0][0]
            sql = f"SELECT a, b FROM strm LIMIT {total_rows}"

            def row_hash(rows_iter) -> tuple:
                h = hashlib.sha256()
                n = 0
                for row in rows_iter:
                    h.update(repr(row).encode())
                    n += 1
                return h.hexdigest(), n

            gc.collect()
            rss0 = _vm_rss_mb()
            peak = rss0
            h = hashlib.sha256()
            n_streamed = 0
            chunks = 0
            final = None
            t0 = time.perf_counter()
            for chunk in broker.execute_stream(sql):
                if chunk.get("type") == "rows":
                    for row in chunk["rows"]:
                        h.update(repr(row).encode())
                        n_streamed += 1
                    chunks += 1
                    rss = _vm_rss_mb()
                    if rss > peak:
                        peak = rss
                elif chunk.get("type") == "final":
                    final = chunk
            stream_s = time.perf_counter() - t0
            hash_stream = h.hexdigest()
            part["rows"] = n_streamed
            part["chunks"] = chunks
            part["stream_s"] = round(stream_s, 2)
            part["stream_mrows_per_s"] = round(
                n_streamed / stream_s / 1e6, 2) if stream_s else 0.0
            part["rss_before_mb"] = round(rss0, 1)
            part["rss_peak_mb"] = round(peak, 1)
            part["stream_rss_delta_mb"] = round(peak - rss0, 1)
            if final is None or final.get("exceptions"):
                violations.append(
                    f"frontdoor: streaming SELECT errored: "
                    f"{(final or {}).get('exceptions')}")
            if not (final or {}).get("streamed"):
                violations.append(
                    "frontdoor: SELECT did not take the true streaming "
                    "path (buffered fallback)")
            if n_streamed != total_rows:
                violations.append(
                    f"frontdoor: streamed {n_streamed} rows, table has "
                    f"{total_rows}")
            if part["stream_rss_delta_mb"] >= 256.0:
                violations.append(
                    f"frontdoor: streaming RSS delta "
                    f"{part['stream_rss_delta_mb']}MB >= 256MB")

            # buffered comparison AFTER the RSS window: same query, whole
            # result materialized — the rows must hash identically in
            # identical order
            t0 = time.perf_counter()
            buffered = broker.execute(sql)
            part["buffered_s"] = round(time.perf_counter() - t0, 2)
            if buffered.get("exceptions"):
                violations.append(
                    f"frontdoor: buffered SELECT errored: "
                    f"{buffered['exceptions']}")
            else:
                hash_buf, n_buf = row_hash(
                    iter(buffered["resultTable"]["rows"]))
                part["bit_identical"] = (
                    hash_buf == hash_stream and n_buf == n_streamed)
                if not part["bit_identical"]:
                    violations.append(
                        f"frontdoor: streamed rows != buffered rows "
                        f"(hash {hash_stream[:12]} vs {hash_buf[:12]}, "
                        f"n {n_streamed} vs {n_buf})")
            return part
        finally:
            if broker is not None:
                broker.close()
            if server is not None:
                try:
                    server.stop()
                except Exception:
                    pass
            shutil.rmtree(base, ignore_errors=True)

    # ---- C. fleet-fair admission via gossiped spend ----------------------
    def fairness() -> dict:
        part: dict = {}
        base = tempfile.mkdtemp(prefix="pinot_tpu_frontdoor_fair_")
        # small tenant budget + throttled spray: the phase measures the
        # ADMISSION wall, so the offered load must overrun the budget
        # (rejections engage) without the spray's own broker overhead
        # (parse/admit/log per request) starving tenant B of CPU — on a
        # small box an unthrottled spray fakes a fairness failure out of
        # plain core contention
        rate, burst, hb_s = 10.0, 5.0, 0.25
        server = None
        brokers = []
        fleets = []
        try:
            registry = ClusterRegistry()
            controller = Controller(registry, os.path.join(base, "ds"))
            rng = np.random.default_rng(20)
            # tenant B pays a real scan (heavy enough that its p99 is
            # its own work, not scheduler noise); tenant A's spray is a
            # near-free lookup so ADMISSION, not CPU, is what bounds it
            schema_b = Schema.build(
                name="fair", dimensions=[("region", DataType.STRING)],
                metrics=[("v", DataType.INT)])
            cfg_b = TableConfig(table_name="fair")
            for i in range(4):
                cols = {
                    "region": np.array(["na", "eu", "apac", "latam"])[
                        rng.integers(0, 4, 150_000)],
                    "v": rng.integers(1, 500, 150_000).astype(np.int32),
                }
                build_segment(schema_b, cols, os.path.join(base, f"f{i}"),
                              cfg_b, f"fair_s{i}")
            schema_a = Schema.build(
                name="ping", dimensions=[],
                metrics=[("x", DataType.INT)])
            cfg_a = TableConfig(table_name="ping")
            build_segment(schema_a,
                          {"x": np.arange(1000, dtype=np.int32)},
                          os.path.join(base, "p0"), cfg_a, "ping_s0")
            server = ServerInstance("fd_fair_srv", registry,
                                    os.path.join(base, "sd"),
                                    device_executor=None)
            server.start()
            controller.add_table(cfg_b, schema_b)
            for i in range(4):
                controller.upload_segment("fair", os.path.join(base,
                                                               f"f{i}"))
            controller.add_table(cfg_a, schema_a)
            controller.upload_segment("ping", os.path.join(base, "p0"))
            t_end = time.time() + 60
            while time.time() < t_end:
                tf = server.engine.tables.get("fair_OFFLINE")
                tp = server.engine.tables.get("ping_OFFLINE")
                if tf is not None and len(tf.segments) == 4 \
                        and tp is not None and len(tp.segments) == 1:
                    break
                time.sleep(0.1)
            else:
                raise RuntimeError("frontdoor: fairness segments never "
                                   "loaded")
            for name in ("fd_fair_a", "fd_fair_b"):
                bk = Broker(registry, broker_id=name, timeout_s=15.0,
                            admission=TenantAdmissionController(
                                rate_qps=rate, burst=burst))
                brokers.append(bk)
                fm = BrokerFleetMember(registry, bk,
                                       heartbeat_interval_ms=int(hb_s * 1e3))
                fm.start()
                fleets.append(fm)
            sql_a = "SELECT COUNT(*) FROM ping"
            sql_b = ("SELECT region, SUM(v) FROM fair GROUP BY region "
                     "ORDER BY region")
            for bk in brokers:
                r = bk.execute(sql_b, principal="tenantB")
                if r.get("exceptions"):
                    raise RuntimeError(f"frontdoor fairness warmup: "
                                       f"{r['exceptions']}")

            def paced_b(n: int, pace_s: float = 0.15) -> list:
                lats = []
                next_t = time.perf_counter()
                for k in range(n):
                    sleep = next_t - time.perf_counter()
                    if sleep > 0:
                        time.sleep(sleep)
                    next_t += pace_s
                    t0 = time.perf_counter()
                    r = brokers[k % 2].execute(sql_b, principal="tenantB")
                    if not r.get("exceptions"):
                        lats.append((time.perf_counter() - t0) * 1e3)
                return lats

            def run_round() -> tuple:
                base_lats = paced_b(24)
                p99_base = float(np.percentile(base_lats, 99)) \
                    if base_lats else 0.0
                # pre-drain: burn tenant A's cold-start burst on BOTH
                # brokers, then give gossip one interval to converge —
                # the measured window tests the steady state the bound
                # is written for
                stop = _threading.Event()
                admitted = [0]
                rejected = [0]
                lock = _threading.Lock()

                def spray(bk):
                    while not stop.is_set():
                        r = bk.execute(sql_a, principal="tenantA")
                        with lock:
                            if r.get("exceptions"):
                                rejected[0] += 1
                            else:
                                admitted[0] += 1
                        time.sleep(0.04)

                for bk in brokers:
                    for _ in range(int(2 * burst)):
                        bk.execute(sql_a, principal="tenantA")
                time.sleep(2 * hb_s)
                admitted[0] = rejected[0] = 0
                threads = [_threading.Thread(target=spray, args=(bk,))
                           for bk in brokers]
                t_start = time.perf_counter()
                for t in threads:
                    t.start()
                with_lats = paced_b(24)
                stop.set()
                for t in threads:
                    t.join()
                window_s = time.perf_counter() - t_start
                p99_with = float(np.percentile(with_lats, 99)) \
                    if with_lats else 0.0
                return (p99_base, p99_with, admitted[0], rejected[0],
                        window_s, len(base_lats), len(with_lats))

            (p99_base, p99_with, admitted, rejected, window_s,
             n_base, n_with) = run_round()
            drift = (p99_with - p99_base) / max(p99_base, 50.0)
            if drift >= 0.25:
                # contention-drift retry: one more full round — on a
                # busy shared box a single background burst during
                # either window fakes a fairness failure
                part["retried"] = True
                (p99_base, p99_with, admitted, rejected, window_s,
                 n_base, n_with) = run_round()
                drift = (p99_with - p99_base) / max(p99_base, 50.0)
            # fleet-wide bound: one logical budget (rate*T), plus the
            # burst the fleet may legitimately hold, plus one heartbeat
            # of refill PER PEER of gossip lag, plus a small pacing slack
            bound = rate * window_s + burst + 2 * rate * hb_s + 8
            no_gossip = 2 * rate * window_s
            part.update({
                "rate_qps": rate, "burst": burst,
                "heartbeat_s": hb_s,
                "window_s": round(window_s, 2),
                "tenantA_admitted": admitted,
                "tenantA_rejected": rejected,
                "admit_bound": round(bound, 1),
                "no_gossip_would_admit": round(no_gossip, 1),
                "tenantB_p99_base_ms": round(p99_base, 1),
                "tenantB_p99_with_spray_ms": round(p99_with, 1),
                "tenantB_p99_drift": round(drift, 3),
                "samples": {"base": n_base, "with": n_with},
            })
            # each broker must have OBSERVED its peer's tenant-A spend
            # (the gossip is what makes the fleet bound reachable at all)
            part["gossip_active"] = all(
                any(seen.get("tenantA", 0) > 0
                    for seen in bk.admission._peer_spend_seen.values())
                for bk in brokers)
            if not part["gossip_active"]:
                violations.append(
                    "frontdoor: brokers never observed peer tenant spend "
                    "(fleet gossip inactive)")
            if admitted > bound:
                violations.append(
                    f"frontdoor: tenant A admitted {admitted} across 2 "
                    f"brokers in {window_s:.1f}s > fleet bound "
                    f"{bound:.0f} (no-gossip would be ~{no_gossip:.0f})")
            if not rejected:
                violations.append(
                    "frontdoor: tenant A spray was never rejected — the "
                    "admission wall is not engaging")
            if drift >= 0.25:
                violations.append(
                    f"frontdoor: tenant B p99 drifted {drift:.1%} under "
                    f"tenant A spray (base {p99_base:.0f}ms -> "
                    f"{p99_with:.0f}ms; bar: <25%)")
            return part
        finally:
            for fm in fleets:
                fm.stop()
            for bk in brokers:
                bk.close()
            if server is not None:
                try:
                    server.stop()
                except Exception:
                    pass
            shutil.rmtree(base, ignore_errors=True)

    scaling_part = broker_scaling()
    detail["broker_scaling"] = scaling_part
    # benchdiff's gated headline keys live at the section top level
    detail["qps2_over_qps1"] = scaling_part.get("qps2_over_qps1", 0.0)
    stream_part = streaming()
    detail["streaming"] = stream_part
    detail["stream_rss_delta_mb"] = stream_part.get(
        "stream_rss_delta_mb", 0.0)
    detail["fairness"] = fairness()
    detail["note"] = (
        "A: cache-hot fixed query via client rotation over 1 vs 2 broker "
        "OS processes discovered from the registry, gain normalized by "
        "the box's own 2-process ceiling; B: 10M-row SELECT streamed "
        "through the chunked cursor path with per-chunk VmRSS sampling, "
        "hash-compared against the buffered run; C: 2 in-process brokers "
        "gossip per-tenant spend over fleet heartbeats while tenant A "
        "sprays both and tenant B runs paced scans")
    return detail, violations


def bench_observability(n_queries: int = 24):
    """detail.observability: the flight-recorder phase (ISSUE 7). A
    2-server in-process cluster serves a device group-by; the phase runs
    the SAME query untraced and traced (SET trace=true) and gates on:

    - disabled-trace overhead < 2%: the no-op span cost per query-path
      span count, measured directly, against the untraced p50 — tracing
      machinery must be free when off;
    - phase-sum reconciliation: each server's top-level spans must cover
      >= 90% of its reported server.total wall (drift > 10% means a
      phase the ladder doesn't see).

    The per-phase p50 breakdown (queue / compile / gather / kernel /
    link / reduce) lands in the BENCH json so future rounds can track
    the ROADMAP-1 link-floor attack against real per-phase numbers.
    Runnable standalone: ``python -m bench --phase observability``
    (exit 5 on violation)."""
    import shutil

    from pinot_tpu.broker.broker import Broker
    from pinot_tpu.cluster.registry import ClusterRegistry
    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import TableConfig
    from pinot_tpu.common.trace import span, top_level_spans
    from pinot_tpu.controller.controller import Controller
    from pinot_tpu.server.server import ServerInstance
    from pinot_tpu.storage.creator import build_segment
    from pinot_tpu.tools.querylog import phase_breakdown

    base = tempfile.mkdtemp(prefix="pinot_tpu_obs_")
    detail: dict = {}
    violations: list = []
    registry = ClusterRegistry()
    controller = Controller(registry, os.path.join(base, "ds"))
    servers = [
        ServerInstance(f"osrv_{i}", registry, os.path.join(base, f"s{i}"))
        for i in range(2)
    ]
    for s in servers:
        # the phase waterfall must keep observing gather/kernel/link on
        # every iteration: partials-cache hits skip those phases and
        # would hollow out the round-over-round breakdown this phase
        # exists to record (cache-hot latency is detail.subrtt's metric)
        if s.engine.device is not None:
            s.engine.device.partials_cache_enabled = False
        # fast heartbeats so the heat snapshot (ISSUE 11) lands inside
        # the phase's runtime rather than the 2s default cadence
        s.heartbeat_interval_s = 0.3
        s.start()
    broker = Broker(registry, timeout_s=30.0)
    try:
        schema = Schema.build(
            name="obs",
            dimensions=[("region", DataType.STRING)],
            metrics=[("amount", DataType.INT)],
        )
        cfg = TableConfig(table_name="obs", replication=1)
        controller.add_table(cfg, schema)
        rng = np.random.default_rng(11)
        n_seg, rows_per = 4, 200_000
        for i in range(n_seg):
            cols = {
                "region": np.array(["na", "eu", "apac", "latam"])[
                    rng.integers(0, 4, rows_per)],
                "amount": rng.integers(1, 500, rows_per).astype(np.int32),
            }
            d = os.path.join(base, f"up_s{i}")
            build_segment(schema, cols, d, cfg, f"obs_s{i}")
            controller.upload_segment("obs", d)
        t_end = time.time() + 30
        while time.time() < t_end:
            if len(registry.external_view("obs_OFFLINE")) == n_seg:
                break
            time.sleep(0.05)
        else:
            raise RuntimeError("observability phase: segments never loaded")

        plain = ("SELECT region, COUNT(*), SUM(amount) FROM obs "
                 "GROUP BY region ORDER BY region")
        traced = "SET trace = true; " + plain

        def run(sql, n):
            lats = []
            last = None
            for _ in range(n):
                t0 = time.perf_counter()
                last = broker.execute(sql)
                lats.append((time.perf_counter() - t0) * 1e3)
                if last.get("exceptions"):
                    raise RuntimeError(f"query failed: {last['exceptions']}")
            return lats, last

        run(plain, 2)   # warm: jit-compile both servers' templates
        run(traced, 1)  # warm the traced form (block_until_ready path)
        lats_off, _ = run(plain, n_queries)
        p50_off = float(np.percentile(lats_off, 50))

        lats_on, _ = run(traced, n_queries)
        p50_on = float(np.percentile(lats_on, 50))

        # per-server coverage + phase waterfall from a fresh traced set
        coverages = []
        phase_samples: dict = {}
        for _ in range(n_queries):
            r = broker.execute(traced)
            info = r.get("traceInfo") or {}
            for inst, spans in info.items():
                if inst == "broker":
                    continue
                total = next((s["durationMs"] for s in spans
                              if s["phase"].endswith(".total")), None)
                if not total:
                    continue
                cov = sum(s["durationMs"]
                          for s in top_level_spans(spans)) / total
                coverages.append(cov)
            for k, v in phase_breakdown({"traceInfo": info}).items():
                phase_samples.setdefault(k, []).append(v)

        # disabled-span micro cost: the whole query path records ~40
        # spans across broker + 2 servers; tracing off must cost no more
        # than SPAN_COUNT no-op spans per query
        SPAN_COUNT = 40
        reps = 100_000
        t0 = time.perf_counter()
        for _ in range(reps):
            with span("bench.noop"):
                pass
        per_span_ms = (time.perf_counter() - t0) / reps * 1e3
        overhead_pct = SPAN_COUNT * per_span_ms / p50_off * 100.0

        min_cov = min(coverages) if coverages else 0.0
        med_cov = float(np.percentile(coverages, 50)) if coverages else 0.0
        detail.update({
            "untraced_p50_ms": round(p50_off, 2),
            "traced_p50_ms": round(p50_on, 2),
            "disabled_span_cost_us": round(per_span_ms * 1e3, 3),
            "disabled_overhead_pct": round(overhead_pct, 4),
            "phase_coverage_min": round(min_cov, 4),
            "phase_coverage_p50": round(med_cov, 4),
            "phase_coverage_mean": round(
                float(np.mean(coverages)) if coverages else 0.0, 4),
            "phase_p50_ms": {
                k: round(float(np.percentile(v, 50)), 3)
                for k, v in sorted(phase_samples.items())
            },
            "note": (
                "coverage = sum of a server's top-level phase spans / its "
                "server.total wall; phase_p50_ms sums each phase across "
                "both servers per query (queue/compile/gather/kernel/"
                "link/reduce — the ROADMAP-1 link-floor waterfall)"),
        })
        if overhead_pct > 2.0:
            violations.append(
                f"disabled-trace overhead {overhead_pct:.3f}% > 2% of the "
                f"untraced p50 ({p50_off:.2f}ms)")
        # gate on the MEDIAN: a single sample preempted between spans on
        # an oversubscribed dev box is scheduler noise, not a phase the
        # ladder fails to see; min still rides in the detail
        if med_cov < 0.90:
            violations.append(
                f"phase-sum reconciliation drift: median per-server span "
                f"coverage {med_cov:.3f} < 0.90 of server.total")

        # ---- EXPLAIN ANALYZE smoke (ISSUE 11) --------------------------
        # the new instrumentation must execute through the broker,
        # render a per-kernel GB/s-vs-HBM-peak line, and leave the query
        # results bit-identical to the plain form
        ea = broker.execute("EXPLAIN ANALYZE " + plain)
        ea_rows = (ea.get("resultTable") or {}).get("rows") or []
        ea_lines = [r[0] for r in ea_rows]
        plain_resp = broker.execute(plain)
        analyzed = (ea.get("analyzedResponse") or {}).get("resultTable")
        bit_identical = analyzed == plain_resp.get("resultTable")
        kernel_lines = [ln for ln in ea_lines if "GB/s" in ln]
        detail["explain_analyze"] = {
            "lines": len(ea_lines),
            "kernel_lines": len(kernel_lines),
            "sample_kernel_line": (kernel_lines[0].strip()
                                   if kernel_lines else None),
            "bit_identical": bool(bit_identical),
        }
        if ea.get("exceptions") or not ea_lines:
            violations.append(
                f"EXPLAIN ANALYZE smoke failed: "
                f"{ea.get('exceptions') or 'no plan rows'}")
        if not any("% of HBM peak" in ln for ln in kernel_lines):
            violations.append(
                "EXPLAIN ANALYZE rendered no per-kernel "
                "'GB/s (x% of HBM peak)' line")
        if not bit_identical:
            violations.append(
                "EXPLAIN ANALYZE results not bit-identical to the "
                "non-ANALYZE form")

        # ---- roofline detail (ISSUE 11) --------------------------------
        # per-kernel achieved GB/s vs the probed peak, merged across the
        # in-process servers' executors — lands top-level as
        # detail.roofline so benchdiff can gate per-kernel deltas
        from pinot_tpu.ops import roofline as _rl

        merged_kernels: dict = {}
        for s in servers:
            dev = s.engine.device
            if dev is None:
                continue
            for label, agg in dev.roofline_stats()["kernels"].items():
                m = merged_kernels.setdefault(
                    label, {"queries": 0, "cache_hits": 0,
                            "bytes_moved": 0, "kernel_ms": 0.0,
                            "link_ms": 0.0})
                for k in m:
                    m[k] += agg.get(k, 0)
        peak = _rl.peak_if_probed()
        for label, m in merged_kernels.items():
            m["kernel_ms"] = round(m["kernel_ms"], 3)
            m["link_ms"] = round(m["link_ms"], 3)
            if m["kernel_ms"] > 0:
                gbps = m["bytes_moved"] / (m["kernel_ms"] / 1e3) / 1e9
                m["gbps"] = round(gbps, 3)
                pct = _rl.pct_of_peak(gbps, peak)
                if pct is not None:
                    m["pct_of_peak"] = pct
        detail["roofline"] = {
            "peak_gbps": round(peak, 1) if peak else None,
            "kernels": merged_kernels,
        }
        if not merged_kernels:
            violations.append("roofline accounting recorded no kernels")

        # ---- segment-temperature snapshot (ISSUE 11) -------------------
        from pinot_tpu.controller.controller import aggregate_heat

        heat = {}
        t_end = time.time() + 10
        while time.time() < t_end:
            heat = aggregate_heat(registry, "obs")
            if heat.get("segments"):
                break
            time.sleep(0.2)
        detail["heat"] = {
            "instancesReporting": heat.get("instancesReporting", 0),
            "segments": dict(list(
                (heat.get("segments") or {}).items())[:8]),
        }
        if not heat.get("segments"):
            violations.append(
                "segment-temperature telemetry: no heat reported via "
                "heartbeats within 10s")
    finally:
        broker.close()
        for s in servers:
            try:
                s.stop(drain_timeout_s=0.2)
            except Exception:
                pass
        shutil.rmtree(base, ignore_errors=True)
    return detail, violations


def _load_micro_reference():
    """BENCH_r05 micro mrows_per_s per kernel: prefer the recorded
    BENCH_r05.json (driver wrapper: parsed.detail.micro, falling back to
    brace-matching the stdout tail), else the embedded constants."""
    path = os.environ.get(
        "PINOT_TPU_MICRO_REF",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_r05.json"))
    try:
        with open(path) as f:
            doc = json.load(f)
        parsed = doc.get("parsed")
        micro = None
        if isinstance(parsed, dict):
            micro = parsed.get("detail", {}).get("micro")
        if micro is None:
            tail = doc.get("tail", "")
            key = '"micro":'
            i = tail.find(key)
            j = tail.find("{", i) if i >= 0 else -1
            if j >= 0:
                depth, k = 0, j
                while k < len(tail):
                    if tail[k] == "{":
                        depth += 1
                    elif tail[k] == "}":
                        depth -= 1
                        if depth == 0:
                            break
                    k += 1
                try:
                    micro = json.loads(tail[j:k + 1])
                except ValueError:
                    micro = None
    except (OSError, ValueError, AttributeError, TypeError):
        # a corrupt/truncated recorded reference must degrade to the
        # embedded floors, never abort the whole bench run
        return dict(_MICRO_R05_REFERENCE), "embedded"
    if not isinstance(micro, dict):
        return dict(_MICRO_R05_REFERENCE), "embedded"
    ref = {k: v.get("mrows_per_s") for k, v in micro.items()
           if isinstance(v, dict) and isinstance(v.get("mrows_per_s"),
                                                 (int, float))}
    # kernels first recorded AFTER the reference round gate against their
    # embedded floors (e.g. narrow_unpack from round 9) — a recorded
    # reference row, once present, always wins
    for k, floor in _MICRO_R05_REFERENCE.items():
        ref.setdefault(k, floor)
    return ref, path


def micro_regression_gate(micro: dict, tolerance: float = 0.25):
    """Compare the micro kernels against the BENCH_r05 reference: a kernel
    REGRESSES when its mrows/s drops more than ``tolerance`` below the
    reference. Kernels without a reference row OR an embedded floor
    (added after r05, e.g. the radix primitives) are skipped — they gate
    from the round that first records them; kernels with an embedded
    floor (blockskip_compact, narrow_unpack) gate against it until a
    recorded reference takes over. Returns (regressions,
    reference_source)."""
    ref, source = _load_micro_reference()
    regressions = {}
    for kernel, ref_rate in ref.items():
        now = micro.get(kernel)
        if not isinstance(now, dict):
            continue
        rate = now.get("mrows_per_s")
        if not isinstance(rate, (int, float)):
            continue
        if rate < ref_rate * (1.0 - tolerance):
            regressions[kernel] = {
                "reference_mrows_per_s": ref_rate,
                "now_mrows_per_s": rate,
                "ratio": round(rate / ref_rate, 3),
            }
    return regressions, source


def main():
    import argparse

    ap = argparse.ArgumentParser(description="pinot-tpu bench")
    ap.add_argument(
        "--phase",
        choices=("full", "faults", "observability", "join", "subrtt",
                 "cluster", "tiering", "overload", "adaptive",
                 "frontdoor"),
        default="full",
        help="'faults' / 'observability' / 'join' / 'subrtt' / 'cluster' "
             "/ 'tiering' / 'overload' / 'adaptive' / 'frontdoor' run "
             "ONLY that phase (no dataset build) so CI can gate on each "
             "standalone")
    args = ap.parse_args()
    if args.phase == "frontdoor":
        detail, violations = bench_frontdoor()
        print(json.dumps({"metric": "frontdoor-phase standalone",
                          "detail": {"frontdoor": detail}}))
        if violations:
            print(f"frontdoor gate FAILED: {json.dumps(violations)}",
                  file=sys.stderr)
            sys.exit(12)
        return
    if args.phase == "adaptive":
        detail, violations = bench_adaptive()
        print(json.dumps({"metric": "adaptive-phase standalone",
                          "detail": {"adaptive": detail}}))
        if violations:
            print(f"adaptive gate FAILED: {json.dumps(violations)}",
                  file=sys.stderr)
            sys.exit(11)
        return
    if args.phase == "overload":
        detail, violations = bench_overload()
        print(json.dumps({"metric": "overload-phase standalone",
                          "detail": {"overload": detail}}))
        if violations:
            print(f"overload gate FAILED: {json.dumps(violations)}",
                  file=sys.stderr)
            sys.exit(10)
        return
    if args.phase == "tiering":
        detail, violations = bench_tiering()
        print(json.dumps({"metric": "tiering-phase standalone",
                          "detail": {"tiering": detail}}))
        if violations:
            print(f"tiering gate FAILED: {json.dumps(violations)}",
                  file=sys.stderr)
            sys.exit(9)
        return
    if args.phase == "cluster":
        detail, violations = bench_cluster()
        print(json.dumps({"metric": "cluster-phase standalone",
                          "detail": {"cluster": detail}}))
        if violations:
            print(f"cluster gate FAILED: {json.dumps(violations)}",
                  file=sys.stderr)
            sys.exit(8)
        return
    if args.phase == "subrtt":
        detail, violations = bench_subrtt()
        print(json.dumps({"metric": "subrtt-phase standalone",
                          "detail": {"subrtt": detail}}))
        if violations:
            print(f"subrtt gate FAILED: {json.dumps(violations)}",
                  file=sys.stderr)
            sys.exit(7)
        return
    if args.phase == "join":
        detail, violations = bench_join()
        print(json.dumps({"metric": "join-phase standalone",
                          "detail": {"join": detail}}))
        if violations:
            print(f"join gate FAILED: {json.dumps(violations)}",
                  file=sys.stderr)
            sys.exit(6)
        return
    if args.phase == "faults":
        detail, violations = bench_faults()
        print(json.dumps({"metric": "faults-phase standalone",
                          "detail": {"faults": detail}}))
        if violations:
            print(f"faults gate FAILED: {json.dumps(violations)}",
                  file=sys.stderr)
            sys.exit(4)
        return
    if args.phase == "observability":
        detail, violations = bench_observability()
        print(json.dumps({"metric": "observability-phase standalone",
                          "detail": {"observability": detail,
                                     "roofline": detail.get("roofline",
                                                            {})}}))
        if violations:
            print(f"observability gate FAILED: {json.dumps(violations)}",
                  file=sys.stderr)
            sys.exit(5)
        return
    os.makedirs(CACHE, exist_ok=True)
    smoke_gate()
    t0 = time.time()
    build_taxi()
    build_ssb()
    build_blockskip()
    build_s = round(time.time() - t0, 1)

    from pinot_tpu.engine.engine import QueryEngine
    from pinot_tpu.storage.segment import ImmutableSegment

    eng = QueryEngine()
    taxi = [
        ImmutableSegment(os.path.join(CACHE, "taxi", f"s{i}"))
        for i in range(TAXI_SEGMENTS)
    ]
    ssb = [
        ImmutableSegment(os.path.join(CACHE, "ssb", f"s{i}"))
        for i in range(SSB_SEGMENTS)
    ]
    for s in taxi:
        eng.add_segment("bench", s)
    for s in ssb:
        eng.add_segment("lineorder", s)
    ssb_rows = sum(s.n_docs for s in ssb)
    taxi_rows = sum(s.n_docs for s in taxi)

    link_floor_ms = round(measure_link_floor() * 1e3, 2)

    bskip = [
        ImmutableSegment(os.path.join(CACHE, "bskip", f"s{i}"))
        for i in range(BSKIP_SEGMENTS)
    ]
    for s in bskip:
        eng.add_segment("bskip", s)

    ssb_detail = bench_suite(eng, SSB_QUERIES)
    taxi_detail = bench_suite(eng, TAXI_QUERIES)
    blockskip_detail = bench_blockskip(eng)
    narrow_detail = bench_narrow(eng, taxi)
    # the link-amortization sweep rides the motivating q2 shape (BENCH_r05:
    # 81.8ms of its 114.9ms p50 was host<->device round trip)
    concurrency_detail = bench_concurrency(eng, SSB_QUERIES["q2_range_sum"])
    realtime_detail = bench_realtime()
    chunklet_detail = bench_chunklet()
    faults_detail, faults_violations = bench_faults()
    observability_detail, observability_violations = bench_observability()
    join_detail, join_violations = bench_join()
    subrtt_detail, subrtt_violations = bench_subrtt()
    # the multi-server scaling ladder self-guards on the core count (a
    # 2-core container runs the 1- and 2-server widths only)
    cluster_detail, cluster_violations = bench_cluster()
    tiering_detail, tiering_violations = bench_tiering()
    overload_detail, overload_violations = bench_overload()
    adaptive_detail, adaptive_violations = bench_adaptive()
    frontdoor_detail, frontdoor_violations = bench_frontdoor()
    micro_detail = bench_micro()
    # micro-kernel regression gate (>25% below the BENCH_r05 reference
    # fails the run AFTER printing, so chunklet work can't silently
    # regress the radix/group-by kernels); PINOT_TPU_MICRO_GATE=off skips
    micro_regressions, micro_ref_source = micro_regression_gate(micro_detail)

    # exactness gate: the cube-routed q4 must answer EXACTLY like BOTH
    # forced-scan q4 variants at full scale (same value hashing on every
    # side — register scatter, in-query sort, and cached projection)
    r_cube = eng.execute(SSB_QUERIES["q4_highcard_hll"])
    for variant in ("q4_scan_hll", "q4_scan_hll_cold"):
        r_scan = eng.execute(SSB_QUERIES[variant])
        if r_cube["resultTable"]["rows"] != r_scan["resultTable"]["rows"]:
            raise SystemExit(
                f"q4 cube != {variant}: {r_cube['resultTable']['rows'][:3]} "
                f"vs {r_scan['resultTable']['rows'][:3]}")

    # HEADLINE: the honest COLD scan frontier — q4 forced off the cube AND
    # off the cached sorted projection (VERDICT r4 weak #1: a number that
    # reads pre-computed structures must not be labeled scan throughput).
    # The projection-assisted steady state (q4_scan_hll, default engine
    # behavior) and the cube figure ride in detail under their own names.
    scan_p50 = ssb_detail["q4_scan_hll_cold"]["p50_ms"] / 1e3
    scan_mrows = ssb_rows / scan_p50 / 1e6
    cube_p50 = ssb_detail["q4_highcard_hll"]["p50_ms"] / 1e3
    cube_mrows = ssb_rows / cube_p50 / 1e6

    # scan-vs-scan baseline (VERDICT r4 weak #3: both sides must take the
    # SAME plan shape): numpy host scan of ONE segment scaled x8, against
    # the device COLD scan p50 — no cube, no projection, on either side
    host = QueryEngine(device_executor=None)
    host.add_segment("lineorder", ssb[0])
    host_lat = run_samples(host, SSB_QUERIES["q4_scan_hll_cold"], 2)
    host_scan_p50 = float(np.percentile(host_lat, 50))
    vs_baseline = host_scan_p50 * SSB_SEGMENTS / scan_p50

    print(
        json.dumps(
            {
                "metric": (
                    "SSB 100M high-card group-by+HLL COLD-SCAN "
                    "throughput (no cube, no cached projection; "
                    "steady-state and cube figures in detail)"
                ),
                "value": round(scan_mrows, 2),
                "unit": "Mrows/s/chip",
                "vs_baseline": round(vs_baseline, 2),
                "detail": {
                    "ssb100m": ssb_detail,
                    "taxi12m": taxi_detail,
                    "blockskip": blockskip_detail,
                    "narrow": narrow_detail,
                    "concurrency": concurrency_detail,
                    "realtime": realtime_detail,
                    "chunklet": chunklet_detail,
                    "faults": faults_detail,
                    "observability": observability_detail,
                    # per-kernel achieved-GB/s vs HBM peak (ISSUE 11) —
                    # top-level so tools/benchdiff.py gates per-kernel
                    # deltas round over round
                    "roofline": observability_detail.get("roofline", {}),
                    "join": join_detail,
                    "subrtt": subrtt_detail,
                    "cluster": cluster_detail,
                    "tiering": tiering_detail,
                    "overload": overload_detail,
                    "adaptive": adaptive_detail,
                    "frontdoor": frontdoor_detail,
                    "micro": micro_detail,
                    "micro_gate": {
                        "reference": micro_ref_source,
                        "tolerance": 0.25,
                        "regressions": micro_regressions,
                    },
                    "cube_accelerated": {
                        "q4_p50_ms": round(cube_p50 * 1e3, 2),
                        "rows_covered_mrows_per_s": round(cube_mrows, 2),
                        "note": (
                            "the cube path answers over O(distinct-combo) "
                            "pre-aggregated rows; rows 'covered', not "
                            "scanned"
                        ),
                    },
                    "ssb_rows": ssb_rows,
                    "taxi_rows": taxi_rows,
                    "dataset_build_s": build_s,
                    "breakdown": {
                        "link_floor_ms": link_floor_ms,
                        "hbm_peak_gbps": HBM_PEAK_GBPS,
                        "note": (
                            "per-query kernel_ms = amortized repeated-"
                            "launch device time; host_ms = wall minus the "
                            "blocking device-wait (measured); link_ms = "
                            "median per-iteration get-wait minus kernel, "
                            "clamped at 0 — the get-wait is now measured "
                            "on the FETCH phase of the async launch/fetch "
                            "split (tunnel round trip; floor is the "
                            "MINIMUM, typical RTT runs above it). "
                            "kernel_gbps/hbm_peak_pct rate the kernel "
                            "against the chip's memory system. The "
                            "breakdown covers the query's FINAL device "
                            "launch — every suite query executes as one "
                            "batched launch solo; under concurrency, "
                            "same-template queries coalesce into one "
                            "vmapped launch per cohort (detail."
                            "concurrency)."
                        ),
                    },
                    "q4_cube_equals_scan": True,
                },
                "baseline_note": (
                    "scan-vs-scan: numpy host executor on 1 segment "
                    "scaled x8 vs the device forced-scan p50 (no cube on "
                    "either side; no published reference numbers — "
                    "BASELINE.md)"
                ),
            }
        )
    )

    if micro_regressions and \
            os.environ.get("PINOT_TPU_MICRO_GATE", "").lower() != "off":
        print(f"micro regression gate FAILED vs {micro_ref_source}: "
              f"{json.dumps(micro_regressions)}", file=sys.stderr)
        sys.exit(3)
    if faults_violations:
        print(f"faults gate FAILED: {json.dumps(faults_violations)}",
              file=sys.stderr)
        sys.exit(4)
    if observability_violations:
        print(f"observability gate FAILED: "
              f"{json.dumps(observability_violations)}", file=sys.stderr)
        sys.exit(5)
    if join_violations:
        print(f"join gate FAILED: {json.dumps(join_violations)}",
              file=sys.stderr)
        sys.exit(6)
    if subrtt_violations:
        print(f"subrtt gate FAILED: {json.dumps(subrtt_violations)}",
              file=sys.stderr)
        sys.exit(7)
    if cluster_violations:
        print(f"cluster gate FAILED: {json.dumps(cluster_violations)}",
              file=sys.stderr)
        sys.exit(8)
    if tiering_violations:
        print(f"tiering gate FAILED: {json.dumps(tiering_violations)}",
              file=sys.stderr)
        sys.exit(9)
    if overload_violations:
        print(f"overload gate FAILED: {json.dumps(overload_violations)}",
              file=sys.stderr)
        sys.exit(10)
    if adaptive_violations:
        print(f"adaptive gate FAILED: {json.dumps(adaptive_violations)}",
              file=sys.stderr)
        sys.exit(11)
    if frontdoor_violations:
        print(f"frontdoor gate FAILED: {json.dumps(frontdoor_violations)}",
              file=sys.stderr)
        sys.exit(12)


if __name__ == "__main__":
    main()
