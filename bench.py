"""Benchmark: the BASELINE.json workloads on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Workload mirrors the reference's JMH macro-bench
(pinot-perf/.../BenchmarkQueries.java:159 — 1.5M-row synthetic segments) and
BASELINE.json configs: a filtered range-scan SUM, a 2-dim GROUP BY with
COUNT/SUM/AVG + DISTINCTCOUNTHLL (NYC-taxi shape), and an IN-filter
aggregation. The headline value is rows scanned per second per chip on the
group-by config; vs_baseline compares against the in-process numpy host
executor on the same machine (stand-in for the CPU reference path until a
real Pinot 32-vCPU run is recorded — BASELINE.md: "published": {}).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

import numpy as np

N_SEGMENTS = 8
ROWS_PER_SEGMENT = 1_500_000
CACHE_DIR = os.path.join(tempfile.gettempdir(), "pinot_tpu_bench_v2")


def build_dataset():
    from pinot_tpu.common.datatypes import DataType
    from pinot_tpu.common.schema import Schema
    from pinot_tpu.common.table_config import (
        IndexingConfig,
        StarTreeIndexConfig,
        TableConfig,
    )
    from pinot_tpu.storage.creator import build_segment

    schema = Schema.build(
        name="bench",
        dimensions=[
            ("zone", DataType.STRING),      # 260 zones (taxi-like)
            ("hour", DataType.INT),         # 24
            ("vendor", DataType.STRING),    # 8
        ],
        metrics=[("fare", DataType.INT), ("distance", DataType.DOUBLE)],
    )
    cfg = TableConfig(
        table_name="bench",
        indexing=IndexingConfig(
            star_tree_configs=[
                StarTreeIndexConfig(
                    dimensions_split_order=["zone", "hour", "vendor"],
                    function_column_pairs=["SUM__fare", "COUNT__*"],
                )
            ]
        ),
    )
    rng = np.random.default_rng(42)
    zones = np.array([f"zone_{i:03d}" for i in range(260)])
    vendors = np.array([f"v{i}" for i in range(8)])
    for i in range(N_SEGMENTS):
        out = os.path.join(CACHE_DIR, f"s{i}")
        if os.path.exists(os.path.join(out, "metadata.json")):
            continue
        n = ROWS_PER_SEGMENT
        cols = {
            "zone": zones[rng.integers(0, 260, n)],
            "hour": rng.integers(0, 24, n).astype(np.int32),
            "vendor": vendors[rng.integers(0, 8, n)],
            "fare": rng.integers(100, 10_000, n).astype(np.int32),
            "distance": np.round(rng.uniform(0.1, 50.0, n), 2),
        }
        build_segment(schema, cols, out, cfg, f"s{i}")
    return schema


QUERIES = {
    "range_sum": "SELECT SUM(fare) FROM bench WHERE fare BETWEEN 1000 AND 5000",
    # the headline raw-scan group-by opts out of the star-tree so the metric
    # measures scan throughput; startree_groupby measures the index path
    "groupby": (
        "SET useStarTree = false; "
        "SELECT zone, hour, COUNT(*), SUM(fare), AVG(distance) FROM bench "
        "GROUP BY zone, hour ORDER BY SUM(fare) DESC, zone, hour LIMIT 10"
    ),
    "startree_groupby": (
        "SELECT zone, hour, COUNT(*), SUM(fare) FROM bench "
        "GROUP BY zone, hour ORDER BY SUM(fare) DESC, zone, hour LIMIT 10"
    ),
    "in_filter": (
        "SELECT COUNT(*), SUM(fare) FROM bench WHERE "
        "vendor IN ('v1','v3','v5') AND hour BETWEEN 7 AND 10"
    ),
    "hll": (
        "SELECT vendor, COUNT(*), DISTINCTCOUNTHLL(zone) FROM bench "
        "GROUP BY vendor ORDER BY vendor"
    ),
}


def run(engine, sql, iters):
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        resp = engine.execute(sql)
        lat.append(time.perf_counter() - t0)
        if resp.get("exceptions"):
            raise RuntimeError(resp["exceptions"])
    return float(np.percentile(lat, 50)), float(np.percentile(lat, 99))


def main():
    os.makedirs(CACHE_DIR, exist_ok=True)
    build_dataset()

    from pinot_tpu.engine.engine import QueryEngine
    from pinot_tpu.storage.segment import ImmutableSegment

    segments = [
        ImmutableSegment(os.path.join(CACHE_DIR, f"s{i}")) for i in range(N_SEGMENTS)
    ]
    total_rows = sum(s.n_docs for s in segments)

    dev = QueryEngine()
    for s in segments:
        dev.add_segment("bench", s)

    # warm (compile + HBM upload), then measure
    detail = {}
    for name, sql in QUERIES.items():
        run(dev, sql, 2)
        p50, p99 = run(dev, sql, 7)
        detail[name] = {"p50_ms": round(p50 * 1e3, 2), "p99_ms": round(p99 * 1e3, 2)}

    headline_p50 = detail["groupby"]["p50_ms"] / 1e3
    rows_per_sec = total_rows / headline_p50

    # CPU stand-in baseline: same query, numpy host path, one segment scaled up
    host = QueryEngine(device_executor=None)
    for s in segments:
        host.add_segment("bench", s)
    host_p50, _ = run(host, QUERIES["groupby"], 3)
    vs_baseline = host_p50 / headline_p50

    print(
        json.dumps(
            {
                "metric": "group-by scan throughput (12M rows, 2-dim groupby+agg)",
                "value": round(rows_per_sec / 1e6, 2),
                "unit": "Mrows/s/chip",
                "vs_baseline": round(vs_baseline, 2),
                "detail": detail,
                "total_rows": total_rows,
                "baseline_note": "vs in-process numpy host path (no published reference numbers; BASELINE.md)",
            }
        )
    )


if __name__ == "__main__":
    main()
